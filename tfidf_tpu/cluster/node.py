"""SearchNode — the symmetric node binary (L2 + L3 + ops API).

Every node runs the same code (like the reference's single Spring Boot
binary); the role is decided at runtime by leader election. The HTTP surface
is API-compatible with the reference so a reference client can switch
unmodified:

Worker data plane (``worker/Worker.java``):
    POST /worker/process      — score a query against the local shard (:175)
    POST /worker/upload       — save + index one document (:125)
    POST /worker/upload-batch — framework addition: bulk text ingest
    GET  /worker/download     — stream a document, traversal-safe (:97)
    GET  /worker/index-size   — load metric in bytes (:147)

Leader control plane (``leader/Leader.java``):
    POST /leader/start        — scatter-gather search, sum-merge (:39-92)
    POST /leader/upload       — least-loaded placement (:153-207)
    POST /leader/upload-batch — framework addition: bulk placement
    GET  /leader/download     — local disk, else probe workers (:95-151)

Ops (``controller/Controllers.java``):
    GET  /api/status          — am-I-leader (:25-29)
    GET  /api/services        — live membership (:30-37)
    GET  /api/metrics         — framework addition: counters + timings

Intentional departures from the reference (flagged per SURVEY.md §3.2):
the scatter fan-out is parallel (the reference loops serially,
``Leader.java:51-70``); result ordering defaults to score-descending with
``result_order="name"`` reproducing the reference's alphabetical TreeMap
(``Leader.java:80-91``).
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
import zlib
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutureTimeout
from http.server import BaseHTTPRequestHandler  # noqa: F401 (re-export)

from tfidf_tpu.cluster.admission import (LANE_BULK, AdmissionController,
                                         ResultCache)
from tfidf_tpu.cluster.autopilot import Autopilot
from tfidf_tpu.cluster.batcher import Coalescer, QueryBatcher
from tfidf_tpu.cluster.coordination import NoNodeError
from tfidf_tpu.cluster.wire import pack_hit_lists, pack_topk_arrays
from tfidf_tpu.cluster.election import LeaderElection
from tfidf_tpu.cluster.fencing import (FENCE_EPOCH_HEADER, FENCE_HEADER,
                                       FENCE_REJECTED_HEADER,
                                       FENCE_STATUS, FenceGuard)
from tfidf_tpu.cluster.nemesis import global_nemesis
from tfidf_tpu.cluster.protover import (PROTO_REJECTED_HEADER,
                                        PROTO_VERSION, proto_headers)
from tfidf_tpu.cluster.placement import PlacementFollower, PlacementMap
from tfidf_tpu.cluster.rebalance import Rebalancer
from tfidf_tpu.cluster.quarantine import (PoisonQuarantine,
                                          poison_fingerprint)
from tfidf_tpu.cluster.registry import (ServiceRegistry,
                                        publish_leader_info,
                                        read_leader_info)
from tfidf_tpu.cluster.resilience import (ClusterResilience,
                                          RpcStatusError,
                                          classify_compute_fault,
                                          is_fence_rejection)
# the read plane (scatter/merge/failover/hedge spine + the shared HTTP
# handler plumbing) lives in cluster/router.py — the scale-out query
# plane: SearchNode hosts it beside its mutation plane; the stateless
# QueryRouter hosts it alone (router.py imports nothing from this
# module at load time, so the split is cycle-free)
from tfidf_tpu.cluster.router import (ScatterReadPlane, _HttpHandlerBase,
                                      _PlaneServer, _linger_bounds,
                                      list_routers)
from tfidf_tpu.engine.engine import Engine
from tfidf_tpu.ops.analyzer import UnsupportedMediaType
from tfidf_tpu.utils import storage
from tfidf_tpu.utils.config import Config
from tfidf_tpu.utils.faults import global_injector
from tfidf_tpu.utils.logging import get_logger
from tfidf_tpu.utils.metrics import global_metrics
from tfidf_tpu.utils.tracing import (global_tracer, propagation_headers,
                                     span_event)

log = get_logger("cluster.node")


# ---- tiny HTTP client helpers (RestTemplate analog, Leader.java:42) ----
#
# Both helpers (and _ScatterClient.post below) pass through the nemesis
# shim (cluster/nemesis.py): an ``origin`` identifies the calling node
# so tests can script per-link partitions/latency/corruption without
# monkeypatching any call site. No rules armed = one emptiness check.
# They are ALSO the trace-propagation seams: when the calling thread
# has an active span, its X-Trace-Id/X-Span-Id ride every outbound
# request (explicit caller headers win on collision), so the trace
# context crosses every leader->worker RPC by construction. Every
# outbound request also stamps X-Proto-Version (cluster/protover.py)
# beside X-Leader-Epoch where that rides, and the assembled headers
# pass through the nemesis skew filter (filter_headers) so the
# rolling-upgrade chaos can mask them per link.

def http_get(url: str, timeout: float = 10.0,
             origin: str | None = None) -> bytes:
    global_nemesis.check_send(origin, url)
    h = proto_headers()
    h.update(propagation_headers())
    h = global_nemesis.filter_headers(origin, url, h)
    req = urllib.request.Request(url, headers=h)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return global_nemesis.filter_reply(origin, url, r.read())


class _ScatterClient:
    """Keep-alive HTTP POST client for the leader's per-query worker RPCs.

    The reference builds a fresh ``RestTemplate`` (and TCP connection) per
    call (``Leader.java:42,127,162``); at hundreds of scatter RPCs per
    second the connection setup + urllib opener machinery becomes a real
    per-query host cost. Fan-out pool threads are long-lived, so one
    persistent connection per (thread, worker) amortizes it away. A
    dropped keep-alive connection is retried once on a fresh one; any
    non-2xx status raises (the caller already treats per-worker errors as
    tolerated scatter failures).

    IDEMPOTENT RPCs ONLY: the stale-connection retry re-sends the whole
    request, and the first attempt may already have reached (even been
    processed by) the worker if the connection died after the body went
    out. Search reads (``/worker/process``, ``/worker/process-batch``)
    are safe; routing an upload through this client could double-apply
    it — uploads go through :func:`http_post` (no retry) instead."""

    # failures that mean "the keep-alive connection went stale between
    # requests" — retried once on a fresh connection. Timeouts and other
    # errors propagate immediately: retrying a hung worker would double
    # the leader's per-worker scatter budget.
    _RETRYABLE = (ConnectionResetError, ConnectionRefusedError,
                  BrokenPipeError)

    def __init__(self) -> None:
        self._tls = threading.local()
        # this node's endpoint identity for the nemesis shim (stamped
        # by SearchNode.start once the server port is known)
        self.origin = ""

    def pop_degraded(self) -> bool:
        """Did the LAST 2xx reply on THIS thread carry
        ``X-Compute-Degraded``? Thread-local (the scatter pool runs one
        RPC per thread at a time), popped by the gatherer right after
        the call returns — so one request's degraded verdict can never
        leak into a concurrent request's health marker."""
        v = getattr(self._tls, "degraded", False)
        self._tls.degraded = False
        return v

    def post(self, base: str, path: str, data: bytes,
             timeout: float = 10.0, live: set[str] | None = None,
             headers: dict[str, str] | None = None) -> bytes:
        import http.client
        global_nemesis.check_send(self.origin, base)
        u = urllib.parse.urlparse(base)
        conns = getattr(self._tls, "conns", None)
        if conns is None:
            conns = self._tls.conns = {}
        if live is not None:   # prune departed workers' idle sockets
            for b in list(conns):
                if b not in live:
                    conns.pop(b).close()
        retryable = self._RETRYABLE + (
            http.client.BadStatusLine, http.client.CannotSendRequest,
            http.client.NotConnected)
        last: Exception | None = None
        for _ in range(2):
            c = conns.get(base)
            if c is not None and c.timeout != timeout:
                # connections cache per (thread, worker) but callers mix
                # timeouts (10s per-query scatter vs scatter_timeout_s
                # batched) — retune the live socket instead of silently
                # keeping the first caller's timeout
                c.timeout = timeout
                if c.sock is not None:
                    c.sock.settimeout(timeout)
            try:
                if c is None:
                    import socket as _socket
                    c = http.client.HTTPConnection(
                        u.hostname, u.port, timeout=timeout)
                    c.connect()
                    # http.client leaves Nagle on; with the unbuffered
                    # small-write HTTP framing both sides use, Nagle +
                    # delayed ACK can add tens of ms per RPC. Cache only
                    # AFTER the connect + setsockopt succeed — a cached
                    # never-connected object would auto-reconnect inside
                    # request() later without TCP_NODELAY
                    c.sock.setsockopt(_socket.IPPROTO_TCP,
                                      _socket.TCP_NODELAY, 1)
                    conns[base] = c
                h = {"Content-Type": "application/json"}
                h.update(proto_headers())
                h.update(propagation_headers())
                h.update(headers or {})
                h = global_nemesis.filter_headers(self.origin, base, h)
                c.request("POST", path, body=data, headers=h)
                r = c.getresponse()
                body = global_nemesis.filter_reply(self.origin, base,
                                                   r.read())
                if r.status >= 300:
                    # typed status error: the resilience layer retries
                    # gateway-transient statuses (502/503/504) and —
                    # only after Retry-After — 429 sheds; never other
                    # 4xx (application), deterministic 500s, or a
                    # worker's honest deadline refusal (the budget
                    # cannot come back — see X-Deadline-Ms)
                    ra = r.getheader("Retry-After")
                    try:
                        ra_s = float(ra) if ra else None
                    except ValueError:
                        ra_s = None   # HTTP-date form: treat as absent
                    fps = r.getheader("X-Poison-Fingerprints") or ""
                    raise RpcStatusError(
                        f"{base}{path}", r.status,
                        deadline_exceeded=(
                            r.getheader("X-Deadline-Exceeded") == "1"),
                        retry_after_s=ra_s,
                        fenced=(r.getheader(FENCE_REJECTED_HEADER)
                                == "1"),
                        proto=(r.getheader(PROTO_REJECTED_HEADER)
                               == "1"),
                        compute_fault=r.getheader("X-Compute-Fault"),
                        poison_fps=tuple(
                            f for f in fps.split(",") if f))
                # host-fallback honesty flows through the gather: a 2xx
                # served by the worker's numpy mirror is exact but
                # degraded — the gatherer pops this per-thread flag
                self._tls.degraded = (
                    r.getheader("X-Compute-Degraded") == "1")
                return body
            except RuntimeError:
                raise
            except retryable as e:
                last = e
                c.close()
                conns.pop(base, None)
            except Exception:
                c.close()
                conns.pop(base, None)
                raise
        raise last if last is not None else RuntimeError("post failed")


def http_post(url: str, data: bytes, content_type: str = "application/json",
              timeout: float = 30.0, headers: dict | None = None,
              origin: str | None = None) -> bytes:
    global_nemesis.check_send(origin, url)
    h = {"Content-Type": content_type}
    h.update(proto_headers())
    h.update(propagation_headers())
    h.update(headers or {})
    h = global_nemesis.filter_headers(origin, url, h)
    req = urllib.request.Request(url, data=data, headers=h)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return global_nemesis.filter_reply(origin, url, r.read())


def http_get_stream(url: str, timeout: float = 30.0,
                    origin: str | None = None):
    """Streaming GET through the shared seams: nemesis-instrumented and
    trace-propagating like :func:`http_get`, but returns the OPEN
    response object for chunked copying (the download probes) instead
    of buffering the body. Reply-corruption nemesis rules do not apply
    to streams — the seam contract here is send-side (partitions,
    latency), which is what the download-path chaos needs.

    (graftcheck protocol finding, fixed: the leader's and router's
    ``/worker/download`` probes previously called ``urlopen`` raw, so
    a scripted partition could never cut the download path and the
    probe hop dropped out of the request trace.)"""
    global_nemesis.check_send(origin, url)
    h = proto_headers()
    h.update(propagation_headers())
    h = global_nemesis.filter_headers(origin, url, h)
    req = urllib.request.Request(url, headers=h)
    return urllib.request.urlopen(req, timeout=timeout)


class WorkerDeadline(RuntimeError):
    """The caller's propagated scatter budget (``X-Deadline-Ms``) ran
    out before scoring began — the worker refuses to start, the handler
    answers 504 + ``X-Deadline-Exceeded: 1``, and the leader's
    resilience layer classifies that as non-retryable."""


class SearchNode(ScatterReadPlane):
    """One node: engine + election + registry + HTTP server.

    Role split (cluster/router.py): the READ plane — the scatter /
    owner-merge / failover / hedge spine behind ``/leader/start`` and
    ``/leader/download`` — is inherited from :class:`ScatterReadPlane`
    and runs on EVERY node; only the placement view differs by role
    (the elected leader routes reads through its authoritative map, a
    non-leader through a watch-refreshed follower view of the durable
    placement znode, so any node serves exact reads without the legacy
    sum-merge's replica double-count). The MUTATION plane — placement
    routing, replication, reconcile/repair, rebalance, deletes — runs
    only on the elected leader; a non-leader forwards front-door
    mutations to the leader published at ``/leader_info``."""

    def __init__(self, config: Config | None = None, coord=None,
                 engine: Engine | None = None, coord_factory=None) -> None:
        """``coord_factory`` (no-arg callable returning a fresh coordination
        client) enables rejoin after a session expiry — the capability the
        reference lacks (its ``Application.process`` only logs and
        ``notifyAll``s on disconnect, ``app/Application.java:49-66``; an
        expired node stays out of the cluster until the pod restarts)."""
        self.config = config or Config()
        # distributed tracing knobs (utils/tracing.py): ring bound +
        # root sampling rate. The tracer is process-global (like the
        # metrics registry); in-process test clusters share one ring.
        global_tracer.configure(
            max_spans=self.config.trace_ring_spans,
            sample_rate=self.config.trace_sample_rate)
        if coord is None and coord_factory is not None:
            coord = coord_factory()
        assert coord is not None, "a coordination client is required"
        self.coord = coord
        self._coord_factory = coord_factory
        self._stopping = False
        self.engine = engine or Engine(self.config)
        self.registry = ServiceRegistry(
            coord, on_change=self._on_membership_change)
        self.election = LeaderElection(coord, callback=self)
        coord.on_session_event(self._on_session_event)
        self._pool = ThreadPoolExecutor(
            max_workers=self.config.fanout_workers,
            thread_name_prefix="fanout")
        # failover/hedge slice re-issues get their OWN pool: on the
        # shared fan-out pool they would queue behind the very laggard
        # primaries they exist to race, turning hedging into a no-op
        # exactly under the saturation it targets
        self._slice_pool = ThreadPoolExecutor(
            max_workers=max(4, self.config.fanout_workers // 2),
            thread_name_prefix="slice")
        self._scatter = _ScatterClient()
        # concurrent /worker/process requests coalesce into one device
        # batch (the kernels are built for [B] batches; the reference
        # scores one query per POST, Worker.java:175-186)
        self.batcher = (QueryBatcher(
            self.engine, max_batch=self.config.query_batch,
            linger_s=self.config.batch_linger_ms / 1e3,
            pipeline=self.config.batch_pipeline,
            **_linger_bounds(self.config.batch_linger_min_ms,
                             self.config.batch_linger_max_ms))
            if self.config.micro_batch else None)
        # leader-side scatter batching: concurrent /leader/start queries
        # group into ONE batched RPC per worker (see leader_search /
        # _scatter_search_batch). The reference fans out one JSON RPC per
        # (query, worker) — Leader.java:51-70 — whose per-query Python
        # cost caps the distributed path far below the engine beneath it.
        # per-owner-set batch keys: the group key is the membership
        # epoch at SUBMIT time, so one coalesced batch never mixes
        # queries from before and after a membership transition — each
        # dispatched batch maps onto exactly one ownership world view
        self.scatter_batcher = (Coalescer(
            self._scatter_search_batch,
            max_batch=self.config.scatter_batch,
            linger_s=self.config.scatter_linger_ms / 1e3,
            pipeline=self.config.scatter_pipeline, name="scatter",
            # items are (query, mode, fusion): one coalesced batch is
            # one ownership world view AND one retrieval plan — sparse,
            # dense and hybrid queries never share a scatter RPC
            group_key=lambda q: (self._cluster_epoch, q[1], q[2])
            if isinstance(q, tuple) else (self._cluster_epoch,
                                          "sparse", None),
            bulk_share=self.config.scatter_bulk_share,
            **_linger_bounds(self.config.scatter_linger_min_ms,
                             self.config.scatter_linger_max_ms))
            if (self.config.scatter_micro_batch
                and not self.config.unbounded_results) else None)
        # overload-survival front door (cluster/admission.py): the
        # /leader/* handlers admit-or-shed BEFORE any work is queued,
        # keyed on the scatter coalescer's queue depth + per-client
        # token buckets. /api/health and /api/metrics never pass
        # through it. The depth signal is the MAX of the left-behind
        # gauge (the k8s HPA signal, refreshed at batch formation) and
        # the coalescer's live backlog — the gauge alone freezes while
        # every dispatcher thread is blocked in a stalled scatter RPC,
        # which is exactly when admitted requests would otherwise queue
        # unboundedly with zero sheds.
        self.admission = AdmissionController(
            self.config,
            depth_fn=lambda: max(
                global_metrics.get("last_scatter_queue_depth", 0.0),
                float(self.scatter_batcher.backlog())
                if self.scatter_batcher is not None else 0.0))
        # leader-side query-result cache, keyed by df_signature(): the
        # (membership epoch, commit generation) token advances on every
        # mutation this leader orchestrates — confirmed upload legs,
        # reconcile deletes, migration flips, membership transitions —
        # so a cached result can never outlive the corpus state it was
        # computed from (no TTL; invalidation rides the same version
        # plumbing that keys the engine's segment view cache)
        # disabled for unbounded-results (parity) configs, mirroring
        # scatter_batcher above: without top-k truncation every cached
        # value is a full-corpus score dict, so the entry-count bound
        # is no memory bound at all (1024 entries x 1M-doc dicts)
        self.result_cache = (ResultCache(self.config.result_cache_entries)
                             if (self.config.result_cache_entries > 0
                                 and not self.config.unbounded_results)
                             else None)
        # traffic-capture tap (utils/storage.py RequestLog): admitted
        # /leader/start requests land in a durable replayable log when
        # the knob names a path — bench.py --replay drives load from it
        self.request_log = (storage.RequestLog(
            self.config.replay_capture_path,
            self.config.replay_capture_max)
            if self.config.replay_capture_path else None)
        # poison-query quarantine (ISSUE 20, cluster/quarantine.py):
        # the read plane's memory of (query, plan) pairs that killed
        # devices on distinct replicas — consulted by _serve_search
        # before any fan-out, fed by _gather_merge's per-worker blame
        self.quarantine = PoisonQuarantine(
            after=self.config.poison_quarantine_after,
            ttl_s=self.config.poison_quarantine_ttl_s,
            max_entries=self.config.poison_quarantine_max)
        self._result_gen = 0
        self._result_gen_lock = threading.Lock()
        # cached role for /api/health: the real is_leader() is a
        # coordination READ (an RPC on the client transport) — the
        # health endpoint must stay responsive while the cluster sheds,
        # so it reports the last role transition instead of blocking
        self._role = "worker"
        # near-real-time commit policy (Lucene NRT readers): uploads
        # defer the commit; the next search commits pending writes first,
        # so read-your-writes visibility matches the reference's
        # commit-per-upload (Worker.java:138) without its O(corpus)
        # per-document cost on bulk ingest
        self._dirty = False
        self._commit_lock = threading.Lock()
        # transient-compile retry budget per query-batch bucket size: a
        # successful search at a bucket refills it; a deterministic
        # compile error (e.g. OOM at a new bucket) drains it and stops
        # being retried, so it cannot double every batch's cost forever
        self._compile_retry_lock = threading.Lock()
        self._compile_retries_used: dict[int, int] = {}
        # leader-side upload placement: TTL cache over worker index
        # sizes + the R-way replica map (re-uploads route to the
        # holders, upserting every copy; see leader_upload and
        # cluster/placement.py). The map is durable: the persister
        # writes it through the coordination substrate so a NEW leader
        # resumes with exact ownership + pending-reconcile state.
        self._size_cache: tuple[float, dict[str, int]] = (0.0, {})
        # worker -> monotonic eviction time: a poll STARTED before the
        # eviction carries pre-failure data for that worker and must not
        # resurrect it into the cache (see _ensure_sizes_fresh)
        self._evicted: dict[str, float] = {}
        self.placement = PlacementMap(
            flush_ms=self.config.placement_flush_ms,
            name=str(self.config.port))
        self.placement.bind_store(lambda: self.coord)
        # leadership fence on every flush (see PlacementMap.persist_gate)
        self.placement.persist_gate = self.is_leader
        # scale-out query plane (cluster/router.py): a NON-leader node
        # serves /leader/start through this read-only follower view of
        # the durable placement znode (watch-refreshed) instead of its
        # empty post-demotion map — without it, a worker answering a
        # read would fall back to the legacy sum-merge across every
        # replica and silently double-count R-replicated documents.
        # None when any-node reads are disabled or the map is not
        # persisted (nothing to follow).
        self.placement_follower: PlacementFollower | None = None
        if (self.config.router_any_node_reads
                and self.config.placement_flush_ms >= 0):
            self.placement_follower = PlacementFollower(
                name=f"n{self.config.port}",
                refresh_ms=self.config.router_refresh_ms,
                stale_ms=self.config.router_stale_ms)
            self.placement_follower.bind_store(lambda: self.coord)
        # elected-leader address cache for the read plane's write
        # forwarding (ScatterReadPlane.leader_url)
        self._leader_cache = (0.0, None)
        # aliases kept for the lock-ordering discipline (and tests):
        # _placement/_moved ARE the placement map's dicts, guarded by
        # _placement_lock == placement.lock
        self._placement_lock = self.placement.lock
        self._placement = self.placement.replicas
        self._moved = self.placement.moved
        # Reconciles run one at a time (_reconcile_serial) so a rejoin
        # cannot interleave with an in-flight recovery.
        self._reconcile_serial = threading.Lock()
        # residue anti-entropy pacing (first pass one period in, like
        # the rebalancer: let the post-election repair settle first)
        self._residue_last = time.monotonic()
        # elastic data plane: live shard migration / drain, riding the
        # sweep loop below (cluster/rebalance.py)
        self.rebalancer = Rebalancer(self)
        # membership epoch: scatter batches group by the value at
        # SUBMIT time, so one coalesced batch never spans a membership
        # transition (one batch = one owner assignment's world view)
        self._cluster_epoch = 0
        # retry policy + per-worker circuit breakers shared by every
        # leader->worker RPC path (cluster/resilience.py)
        self.resilience = ClusterResilience(self.config)
        # LIVE hedge delay: reads on the scatter path go through this
        # attribute (not the frozen config) so the SLO autopilot can
        # track it to the observed scatter p95; initialized to — and
        # reverted to, on the kill switch — the static config value
        self.hedge_ms = float(self.config.scatter_hedge_ms)
        # closed-loop SLO autopilot (cluster/autopilot.py): leader-side
        # controller riding the sweep loop below that tunes hedge_ms,
        # the admission watermarks, the adaptive-linger ceiling, and
        # the gray-failure slow-trip threshold from the live
        # histograms — each with hysteresis, clamps, damping, a
        # decision-audit ring (GET /api/autopilot), and a kill switch
        self.autopilot = Autopilot(self)
        # leadership fencing (cluster/fencing.py): the worker-side
        # guard (highest leader epoch ever seen, durable beside the
        # index so a reboot mid-partition cannot be captured by a
        # deposed leader) and the leader-side epoch stamped on every
        # mutating worker RPC. A fence rejection triggers an immediate
        # step-down (_fence_step_down) — never a retry.
        self.fence = FenceGuard(os.path.join(self.config.index_path,
                                             "fence_epoch.json"))
        self._leader_epoch: int | None = None
        self._fence_lock = threading.Lock()
        self._fence_stepping = False
        # workers that have EVER contributed unmapped (legacy
        # sum-merge) hits: if one of them later fails, the map cannot
        # vouch for its unmapped documents — the degraded marker stays
        # honest even when no live worker echoes those docs (GIL-atomic
        # dict ops; bounded by distinct worker URLs)
        self._legacy_hit_workers: dict[str, float] = {}
        # last-observed scatter health (attempted / responded /
        # circuit-open) for the CLI summary; per-REQUEST markers are
        # returned by leader_search_with_health — the degraded header is
        # stamped from the returned value, never from this shared copy
        self._scatter_health: dict[str, int] = {}
        # periodic reconciliation sweep: retries failed /worker/delete
        # reconciles (ADVICE r5 medium — without it a failed reconcile
        # leaves moved docs double-indexed until the NEXT membership
        # event) — started in start(), runs only while leader
        self._sweep_thread = None
        if (self.config.shard_recovery
                and self.config.reconcile_sweep_interval_s > 0):
            self._sweep_thread = threading.Thread(
                target=self._reconcile_sweep_loop, daemon=True,
                name=f"reconcile-sweep-{self.config.port}")
        # the durable store of placed documents lives BESIDE the served
        # documents dir, never inside it: the leader's own boot re-walk
        # must not index copies of documents that live on other workers
        # (that would double-count them in the scatter sum-merge)
        self._store_dir = os.path.join(self.config.index_path,
                                       "placed_docs")
        # name -> CRC32 of every stored placed document: the reference
        # the integrity scrub verifies against (without an independent
        # record, bit rot in a stored doc is undetectable — the bytes
        # are their own only witness). Flushes are debounced onto the
        # sweep loop's scrub pass.
        self._store_ledger = storage.CrcLedger(
            os.path.join(self.config.index_path, "placed_docs.crc.json"))
        self._scrub_last = time.monotonic()

        # serving-node durability (the reference commits its Lucene index
        # on every upload, Worker.java:138): an on-demand /admin/checkpoint
        # endpoint plus an optional periodic autosave of dirty state
        self.checkpoint_dir = (self.config.checkpoint_path
                               or os.path.join(self.config.index_path,
                                               "checkpoint"))
        self._ckpt_lock = threading.Lock()
        self._ckpt_thread = None
        if self.config.checkpoint_interval_s > 0:
            self._ckpt_thread = threading.Thread(
                target=self._autosave_loop, daemon=True,
                name=f"ckpt-{self.config.port}")

        handler = type("Handler", (_NodeHandler,), {"node": self})
        self.httpd = _NodeServer(
            (self.config.host, self.config.port), handler)
        self.port = self.httpd.server_address[1]
        # the reference builds this from POD_IP + SERVER_PORT env vars
        # (OnElectionAction.java:35-36)
        self.url = f"http://{self.config.host}:{self.port}"
        self._server_thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True,
            name=f"node-{self.port}")

    # ---- lifecycle (app/Application.java:33-46) ----

    def _stamp_net_origin(self, coord) -> None:
        """Identify this node's outbound traffic to the nemesis shim:
        the scatter client and (when the coordination client supports
        it and a test has not already named it) the control-plane
        client share the node's own endpoint identity."""
        self._scatter.origin = self.url
        if getattr(coord, "origin", None) == "":
            coord.origin = self.url

    def start(self, rebuild: bool = True,
              rebuild_newer_than: float | None = None) -> "SearchNode":
        self._server_thread.start()
        self._stamp_net_origin(self.coord)
        if rebuild:   # boot-time re-walk (Worker.java:77-88); after a
            # checkpoint restore only documents written since the save
            # are re-analyzed (idempotent upserts)
            self.engine.build_from_directory(
                newer_than=rebuild_newer_than)
        self.placement.start_persister()
        if self.placement_follower is not None:
            # any-node read plane: follow the durable placement znode
            # (data watch + periodic backstop — cluster/placement.py)
            self.placement_follower.start()
        self.election.volunteer_for_leadership()
        self.election.reelect_leader()
        if self._ckpt_thread is not None:
            self._ckpt_thread.start()
        if self._sweep_thread is not None:
            self._sweep_thread.start()
        log.info("node started", url=self.url,
                 leader=self.election.is_leader())
        return self

    # ---- serving-node checkpoints ----

    def save_checkpoint(self) -> dict:
        """Checkpoint the engine to this node's checkpoint dir (used by
        /admin/checkpoint and the autosave loop). Serialized by a lock —
        overlapping saves would race on the version directory."""
        from tfidf_tpu.engine.checkpoint import save_checkpoint
        with self._ckpt_lock:
            t0 = time.perf_counter()
            save_checkpoint(self.engine, self.checkpoint_dir)
            dt = time.perf_counter() - t0
        global_metrics.inc("checkpoints_saved")
        global_metrics.observe("checkpoint_save", dt)
        return {"dir": self.checkpoint_dir,
                "docs": self.engine.index.num_live_docs,
                "seconds": round(dt, 2)}

    def _autosave_loop(self) -> None:
        interval = self.config.checkpoint_interval_s
        last_state = None
        while not self._stopping:
            time.sleep(interval)
            if self._stopping:
                return
            try:
                # flush deferred upload commits first — otherwise an
                # upload burst with no intervening search leaves _dirty
                # set and the loop re-saves the identical corpus forever
                self.commit_if_dirty()
                state = (self.engine.index.num_live_docs,
                         getattr(self.engine.index, "_gen", None))
                if state == last_state:
                    continue   # nothing new since the last save
                self.save_checkpoint()
                last_state = state
            except Exception as e:
                log.warning("autosave checkpoint failed", err=repr(e))

    def stop(self) -> None:
        self._stopping = True
        self._store_ledger.flush(fsync=False)   # best-effort final flush
        self.placement.stop()
        if self.placement_follower is not None:
            self.placement_follower.stop()
        self.election.resign()
        self.registry.unregister_from_cluster()
        self.httpd.shutdown()
        self.httpd.server_close()
        self._pool.shutdown(wait=False)
        self._slice_pool.shutdown(wait=False)
        if self.batcher is not None:
            self.batcher.stop()
        if self.scatter_batcher is not None:
            self.scatter_batcher.stop()
        if self.request_log is not None:
            self.request_log.close()

    # ---- worker search path (Worker.java:175-186) ----

    def worker_search(self, query: str) -> list:
        """Score one query against the local engine. Default: exact top-k
        through the packed-transfer fast path, micro-batched with
        concurrent requests. ``unbounded_results=True`` restores the
        reference's full-ranking behavior (``Worker.java:230``) for
        parity."""
        self.commit_if_dirty()
        unbounded = self.config.unbounded_results
        if self.batcher is not None:
            return self.batcher.search(query, unbounded=unbounded)
        return self.engine.search(query, unbounded=unbounded)

    # Retry gate classifier: the structured compute-fault taxonomy
    # (cluster/resilience.classify_compute_fault — the same function
    # the engine's health machine and the leader's poison quarantine
    # use, so the three can never drift). Only "compile" (the tunnel's
    # remote-compile flakes, a fresh executable may succeed) and
    # "transient" (one-off dispatch failure) earn the single budgeted
    # retry; "oom" already ran the engine's batch-backoff ladder and
    # "poison" must surface unretried for the leader to quarantine.
    @staticmethod
    def _is_retryable_compute_fault(e: BaseException) -> bool:
        return classify_compute_fault(e) in ("compile", "transient")

    def _compile_bucket(self, n_queries: int) -> int:
        """Query batches pad to power-of-two buckets; the retry budget is
        tracked per bucket because a deterministic compile failure is a
        property of the compiled shape, not of one request."""
        return 1 << max(0, n_queries - 1).bit_length() if n_queries else 0

    def _search_batch_guarded(self, n_queries: int, run,
                              deadline: float | None = None):
        """Shared wrapper for the batched-scatter entrypoints: NRT
        commit, timing, and the transient-compile retry. A failure
        matching the known transient remote-compile signature is
        retried once, with a per-bucket-size budget: a deterministic
        compile error (e.g. OOM at a new bucket) drains the budget and
        then propagates immediately instead of doubling every batch's
        cost forever.

        ``deadline`` (monotonic seconds) is the leader's propagated
        scatter budget: re-checked AFTER the NRT commit (which can eat
        real time) and before every scoring attempt — a batch whose
        caller already gave up must not burn device time nobody will
        merge."""
        self.commit_if_dirty()
        if deadline is not None and time.monotonic() > deadline:
            global_metrics.inc("worker_deadline_refusals")
            raise WorkerDeadline("scatter deadline passed before scoring")
        bucket = self._compile_bucket(n_queries)
        t0 = time.perf_counter()
        try:
            out = run()
        except Exception as e:
            if not self._is_retryable_compute_fault(e):
                raise
            with self._compile_retry_lock:
                used = self._compile_retries_used.get(bucket, 0)
                if used >= self.config.compile_retry_per_bucket:
                    raise   # budget spent: treat as deterministic
                self._compile_retries_used[bucket] = used + 1
            global_metrics.inc("search_compile_retries")
            log.warning("search failed in compilation; retrying once",
                        err=repr(e)[:200], bucket=bucket)
            time.sleep(0.5)
            out = run()
        with self._compile_retry_lock:
            # success refills the bucket's budget: only CONSECUTIVE
            # failures at a bucket look deterministic
            self._compile_retries_used.pop(bucket, None)
        global_metrics.observe("worker_batch_search",
                               time.perf_counter() - t0)
        return out

    def worker_search_batch(self, queries: list[str],
                            k: int | None = None,
                            deadline: float | None = None) -> list[list]:
        """Score an already-formed query batch (the leader's batched
        scatter RPC). Bypasses the micro-batcher — the batch needs no
        linger for company — and runs the engine's batch path directly;
        searches are pure functions of the committed snapshot, so
        concurrent batch RPCs are safe (and their chunks OVERLAP on the
        searcher's shared pipeline executor: batch B's device programs
        dispatch while batch A's packed top-k fetch is still on the
        wire — engine/pipeline.py)."""
        return self._search_batch_guarded(
            len(queries), lambda: self.engine.search_batch(queries, k=k),
            deadline=deadline)

    def worker_search_slice(self, queries: list[str],
                            names: list[str],
                            deadline: float | None = None
                            ) -> list[list[tuple[str, float]]]:
        """Score an ownership SLICE: every matching document among
        ``names`` for each query (the leader's failover / hedged
        re-issue of a dead owner's documents). Exact within the slice —
        the full ranking is computed host-side and filtered, so a
        sliced document can never be truncated out by documents outside
        the slice."""
        nameset = set(names)

        def run() -> list[list[tuple[str, float]]]:
            res = self.engine.search_batch(queries, unbounded=True)
            return [[(h.name, h.score) for h in hits
                     if h.name in nameset] for hits in res]

        out = self._search_batch_guarded(len(queries), run,
                                         deadline=deadline)
        global_metrics.inc("worker_slice_rpcs")
        return out

    def worker_search_batch_wire(self, queries: list[str],
                                 k: int | None = None,
                                 deadline: float | None = None) -> bytes:
        """Batched scatter RPC -> packed wire reply bytes. Fast path:
        the local searcher's raw top-k arrays packed vectorized
        (``search_arrays`` + ``pack_topk_arrays`` — no per-hit
        SearchHit churn on the serving path). Falls back to the
        hit-list path when the engine's searcher lacks the arrays
        entrypoint (mesh layouts) or name-ordered parity results are
        configured; both produce byte-identical wire replies for
        score-ordered results (tests/test_pipeline.py)."""
        got = None
        if (self.config.result_order == "score"
                and getattr(self.engine.searcher, "search_arrays",
                            None) is not None):
            got = self._search_batch_guarded(
                len(queries),
                lambda: self.engine.search_batch_arrays(queries, k=k),
                deadline=deadline)
        if got is None:   # mesh layouts / name-ordered parity configs
            results = self.worker_search_batch(queries, k=k,
                                               deadline=deadline)
            t0 = time.perf_counter()
            body = pack_hit_lists(results)
        else:
            vals, ids, _kk, names = got
            t0 = time.perf_counter()
            body = pack_topk_arrays(vals, ids, names)
        global_metrics.observe("worker_batch_pack",
                               time.perf_counter() - t0)
        return body

    def worker_search_staged_wire(self, queries: list[str],
                                  k: int | None = None,
                                  mode: str = "hybrid",
                                  deadline: float | None = None) -> bytes:
        """Two-stage scatter reply (mode dense|hybrid): ``2n`` hit
        lists on the ordinary packed wire — the first ``n`` are the
        sparse stage (empty lists for mode=dense, keeping the slot
        layout uniform), the last ``n`` the dense stage. Dense lists
        always ride ``pack_hit_lists``, never the arrays fast path:
        ``pack_topk_arrays`` drops scores <= 0, and signed-hash cosines
        are legitimately negative."""
        if mode == "hybrid":
            sparse = self.worker_search_batch(queries, k=k,
                                              deadline=deadline)
        else:
            sparse = [[] for _ in queries]
        dense = self._search_batch_guarded(
            len(queries),
            lambda: self.engine.search_dense_batch(queries, k=k),
            deadline=deadline)
        global_metrics.inc("worker_dense_batches")
        return pack_hit_lists(list(sparse) + list(dense))

    def worker_search_slice_staged(self, queries: list[str],
                                   names: list[str], mode: str,
                                   deadline: float | None = None
                                   ) -> list[list[tuple[str, float]]]:
        """Failover / hedge slice for a staged query: ``2n`` lists in
        the same (sparse block, dense block) layout as the batched
        reply, exact within the slice for BOTH stages — a failover
        must re-issue every stage the dead owner would have run."""
        if mode == "hybrid":
            sparse = self.worker_search_slice(queries, names,
                                              deadline=deadline)
        else:
            sparse = [[] for _ in queries]
            global_metrics.inc("worker_slice_rpcs")
        dmaps = self._search_batch_guarded(
            len(queries),
            lambda: self.engine.search_dense_names(queries, names),
            deadline=deadline)
        dense = [sorted(m.items(), key=lambda kv: (-kv[1], kv[0]))
                 for m in dmaps]
        return list(sparse) + dense

    def notify_write(self) -> None:
        """Mark uncommitted writes (called by the upload handler)."""
        self._dirty = True

    # ---- result-cache generation (cluster/admission.py) ----

    def bump_result_generation(self) -> None:
        """Advance the df-signature commit generation: any mutation
        that could change a score (a confirmed upload leg, a reconcile
        delete, a migration flip, a direct worker-side write) calls
        this, so every cached query result stamped with an older token
        dies at its next lookup."""
        with self._result_gen_lock:
            self._result_gen += 1

    def df_signature(self) -> tuple:
        """The result cache's generation token: (membership epoch,
        commit generation). The epoch component covers everything that
        changes WHICH shards answer (worker death/join shifts
        per-shard df); the generation component covers every commit
        the leader orchestrates on unchanged membership.

        A NON-leader serving reads has no view of the leader's commit
        generation — its token keys on the follower VIEW version
        instead (tagged so a token minted in one role can never
        collide with the other): every observed placement flush — the
        leader flushes after every df-changing commit — invalidates,
        bounding staleness by the flush debounce + watch latency. The
        LOCAL commit generation still rides along: a direct
        ``/worker/*`` write on this node changes its own engine's df
        without any placement flush (the dual-role contract)."""
        if self._role != "leader" and self._follower_active():
            with self._result_gen_lock:
                gen = self._result_gen
            return (self._cluster_epoch,
                    ("view", self.placement_follower.version, gen))
        with self._result_gen_lock:
            gen = self._result_gen
        return (self._cluster_epoch, gen)

    def commit_if_dirty(self) -> None:
        """NRT visibility point: flush deferred upload commits before
        serving a search. Clearing the flag before committing means a
        write landing mid-commit re-dirties and is flushed next time."""
        if self._dirty:
            with self._commit_lock:
                if self._dirty:
                    self._dirty = False
                    try:
                        self.engine.commit()
                    except BaseException:
                        # a failed commit must not leave the node serving
                        # stale pre-upload results forever
                        self._dirty = True
                        raise
        else:
            # a sibling search may have observed the same writes, cleared
            # the flag, and STILL be mid-commit — searching now would see
            # the pre-upload snapshot and break read-your-writes (an
            # upload's 200 means the next search finds it, matching the
            # reference's synchronous commit, Worker.java:138). Barrier
            # on the lock: free when no commit is in flight.
            with self._commit_lock:
                pass

    # ---- session-expiry recovery ----

    def _on_session_event(self, ev) -> None:
        log.warning("coordination session expired", url=self.url)
        if self._stopping or self._coord_factory is None:
            return
        threading.Thread(target=self._rejoin, daemon=True,
                         name=f"rejoin-{self.port}").start()

    def _rejoin(self) -> None:
        """Reconnect with a fresh session and re-enter election + registry.
        All prior ephemerals are gone with the old session, so this is a
        clean re-volunteer (the role may change: an ex-leader can come back
        as a worker)."""
        delay = 0.2
        while not self._stopping:
            try:
                coord = self._coord_factory()
                self.coord = coord
                self._stamp_net_origin(coord)
                self.registry = ServiceRegistry(
                    coord, on_change=self._on_membership_change)
                self.election = LeaderElection(coord, callback=self)
                coord.on_session_event(self._on_session_event)
                self.election.volunteer_for_leadership()
                self.election.reelect_leader()
                if self.placement_follower is not None:
                    # the old session's data watch died with it: force
                    # a re-arm + refresh on the NEW client (the store
                    # getter reads self.coord dynamically) — without
                    # this the any-node read view would silently fall
                    # back to poll latency forever
                    self.placement_follower._watch_armed = False
                    self.placement_follower._wake.set()
                global_metrics.inc("session_rejoins")
                log.info("rejoined cluster after session expiry",
                         url=self.url, leader=self.election.is_leader())
                # the rebuilt registry's first refresh is "initial
                # population", never a lost-transition — so a worker
                # that died DURING the outage would stay dark forever.
                # Diff the placement map against the fresh view, after
                # a grace period: a registry-wide blip expires EVERY
                # session, and diffing before the other workers finish
                # their own rejoins would re-place the whole corpus
                # only to reconcile it back seconds later.
                if (self.config.shard_recovery
                        and self.election.is_leader()):
                    threading.Thread(
                        target=self._recover_after_rejoin, daemon=True,
                        name=f"shard-recovery-{self.port}").start()
                return
            except Exception as e:
                log.warning("rejoin attempt failed", err=repr(e))
                time.sleep(delay)
                delay = min(delay * 2, 5.0)

    def _recover_after_rejoin(self) -> None:
        time.sleep(max(2 * self.config.session_timeout_s, 1.0))
        if self._stopping or not self.is_leader():
            return
        live = set(self.registry.get_all_service_addresses())
        with self._placement_lock:
            known = {w for ws in self._placement.values() for w in ws}
        lost = known - live
        if lost:
            self._reconcile_membership(lost, set())

    # ---- role transitions (leader/OnElectionAction.java:27-77) ----

    def on_elected_to_be_leader(self) -> None:
        self._role = "leader"   # cached for the non-blocking /api/health
        # leadership epoch, issued at promotion: the election znode's
        # own sequence number (strictly grows across successions —
        # cluster/fencing.py). Stamped on every mutating worker RPC and
        # into the durable placement znode; this node's own worker
        # plane advances its fence NOW so a deposed predecessor cannot
        # write here even before the first fenced RPC arrives.
        epoch = self.election.epoch()
        self._leader_epoch = epoch
        self.placement.epoch = epoch
        if epoch is not None:
            self.fence.observe(epoch)
        # the leader does not serve a shard: leave the worker pool (:30)
        self.registry.unregister_from_cluster()
        self.registry.register_for_updates()
        publish_leader_info(self.coord, self.url)
        global_metrics.inc("elections_won")
        log.info("assumed leader role", url=self.url, epoch=epoch)
        # resume ownership: load the durable placement map (and its
        # pending-reconcile state) off-thread — this callback can run
        # on the watch-dispatch thread, and the load is a coordination
        # read that must not stall other clients' events
        threading.Thread(target=self._resume_placement, daemon=True,
                         name=f"placement-resume-{self.config.port}"
                         ).start()

    def _resume_placement(self) -> None:
        """New-leader resume: merge the persisted placement map into
        memory, then enable persistence (in that order — enabling first
        could let an early flush clobber the znode before it is read),
        reconcile any workers that died while no leader was watching,
        and restore the replication factor.

        The load is retried (bounded) and persistence stays DISABLED if
        it never succeeds: flushing a near-empty in-memory map over the
        predecessor's durable one would permanently strip failover
        coverage from every document placed before this tenure — a
        stale durable map is strictly better than a clobbered one."""
        # fence round FIRST: push the new epoch to every live worker
        # NOW, so a deposed predecessor (possibly still alive behind a
        # partition) cannot land even one more write in the promotion
        # gap — without this, the split-brain window stays open until
        # this leader's first organic mutating RPC happens to reach
        # each worker
        try:
            self._fence_workers()
        except Exception as e:
            log.warning("promotion fence round failed", err=repr(e))
        loaded = self.config.placement_flush_ms < 0   # nothing to load
        if not loaded:
            delay = 0.2
            deadline = time.monotonic() + 30.0
            while not self._stopping:
                try:
                    self.placement.load()
                    loaded = True
                    break
                except Exception as e:
                    log.warning("placement map load failed; retrying",
                                err=repr(e))
                    if time.monotonic() > deadline:
                        break
                    time.sleep(delay)
                    delay = min(delay * 2, 2.0)
        if loaded:
            self.placement.set_persist_enabled(True)
        else:
            log.warning(
                "placement map load kept failing; placement persistence "
                "stays disabled this tenure (never overwrite the "
                "durable map with an unloaded in-memory one)")
        if self._stopping or not self.config.shard_recovery:
            return
        try:
            if not self.is_leader():
                return
            # resolve a predecessor's in-flight migrations FIRST (abort
            # copying-phase records) so the repair/trim pass below can
            # reclaim their stray copy legs in the same sweep
            self.rebalancer.resume_after_election()
            live = set(self.registry.get_all_service_addresses())
            with self._placement_lock:
                known = {w for ws in self._placement.values()
                         for w in ws}
            lost = known - live
            if lost:
                self._reconcile_membership(lost, set())
            else:
                self.run_replication_repair()
        except Exception as e:
            log.warning("placement resume pass failed", err=repr(e))

    def on_worker(self) -> None:
        self._role = "worker"   # cached for the non-blocking /api/health
        # a demoted node holds no leadership epoch: mutating RPCs it
        # somehow still issues would go unstamped (and its placement
        # flushes are disabled below anyway)
        self._leader_epoch = None
        self.placement.epoch = None
        # a worker must never write the leader's placement state, and
        # a DEMOTED ex-leader must not carry its tenure's map into a
        # possible later re-promotion — the durable znode (written by
        # its successors) is newer than this node's memory, so the map
        # resets and a re-election loads it fresh
        self.placement.set_persist_enabled(False)
        self.placement.reset_for_follower()
        self.registry.register_to_cluster(self.url)
        log.info("assumed worker role", url=self.url)

    def is_leader(self) -> bool:
        return self.election.is_leader()

    # ---- leadership fencing (cluster/fencing.py) ----

    def _fence_workers(self) -> None:
        """Promotion fence round: an empty, epoch-stamped
        ``/worker/delete`` to every live worker advances each worker's
        durable fence to this tenure's epoch — after it lands, no RPC
        from any predecessor can be accepted anywhere. Best-effort per
        worker (an unreachable worker is fenced by this leader's first
        real write to it, or rejects the predecessor anyway once any
        stamped RPC arrives); counted in ``fence_rounds``."""
        if self._leader_epoch is None:
            return
        workers = self.registry.get_all_service_addresses()
        if not workers:
            return
        body = json.dumps({"names": []}).encode()
        fenced = 0
        for w in workers:
            try:
                self._worker_call_fenced(
                    w, lambda w=w: http_post(
                        w + "/worker/delete", body, timeout=10.0,
                        headers=self._epoch_headers(), origin=self.url))
                fenced += 1
            except Exception as e:
                log.warning("promotion fence push failed", worker=w,
                            err=repr(e))
        if fenced:
            global_metrics.inc("fence_rounds")
            log.info("promotion fence round complete", workers=fenced,
                     epoch=self._leader_epoch)

    def _epoch_headers(self) -> dict[str, str]:
        """The fencing token for one mutating worker RPC. Empty when
        this node holds no epoch (not leader / pre-election) — workers
        never fence unstamped requests, so reference clients and
        single-node deployments are untouched."""
        epoch = self._leader_epoch
        return {FENCE_HEADER: str(epoch)} if epoch is not None else {}

    def _worker_call_fenced(self, worker: str, fn):
        """``ClusterResilience.worker_call`` for MUTATING RPCs: a
        leadership-fence rejection (403 + X-Fence-Rejected) triggers an
        immediate step-down — a newer leader exists, so this node's
        epoch can never become valid again; retrying would be the
        split-brain the fence exists to stop. The rejection still
        propagates to the caller as a failed leg (never acked)."""
        try:
            return self.resilience.worker_call(worker, fn)
        except Exception as e:
            if is_fence_rejection(e):
                self._note_fence_rejection(worker, e)
            raise

    def _note_fence_rejection(self, worker: str, e: BaseException) -> None:
        with self._fence_lock:
            if self._fence_stepping:
                return          # a step-down is already in flight
            self._fence_stepping = True
        log.warning("fenced by a newer leader epoch; stepping down",
                    worker=worker, err=repr(e),
                    my_epoch=self._leader_epoch)
        global_metrics.inc("fence_step_downs")
        span_event("fence_rejected", worker=worker,
                   stale_epoch=self._leader_epoch)
        threading.Thread(target=self._fence_step_down, daemon=True,
                         name=f"fence-stepdown-{self.port}").start()

    def _fence_step_down(self) -> None:
        """Deposed-leader demotion: drop all leader authority NOW (in
        memory — no further placement flushes, no stale map carried
        into a later tenure), then resign the election znode and
        re-enter as a fresh candidate (whose new sequence number mints
        a HIGHER epoch, so a re-promotion is safe by construction).
        Coordination may be unreachable — the very partition that got
        us deposed — so re-entry retries with backoff and defers to the
        session-expiry rejoin path the moment it takes over."""
        election = self.election
        try:
            self._leader_epoch = None
            self.placement.epoch = None
            self.placement.set_persist_enabled(False)
            self.placement.reset_for_follower()
            self._role = "worker"
            try:
                election.resign()
            except Exception as e:
                # partitioned from the coordinator: the znode is (or
                # will be) gone with the session anyway
                log.warning("resign after fence failed", err=repr(e))
            delay = 0.1
            while not self._stopping:
                if self.election is not election:
                    return   # a session-expiry rejoin took over
                try:
                    self.election.volunteer_for_leadership()
                    self.election.reelect_leader()
                    log.info("re-entered election after fence "
                             "step-down", url=self.url,
                             leader=self.election.is_leader())
                    return
                except NoNodeError:
                    # our session died during the partition: the
                    # SESSION_EXPIRED event owns recovery (rejoin with
                    # a fresh session)
                    log.info("fence step-down defers to session-expiry "
                             "rejoin")
                    return
                except Exception as e:
                    log.warning("election re-entry after fence failed; "
                                "retrying", err=repr(e))
                    time.sleep(delay)
                    delay = min(delay * 2, 2.0)
        finally:
            with self._fence_lock:
                self._fence_stepping = False

    # ---- read plane (cluster/router.py ScatterReadPlane) ----
    #
    # leader_search / leader_search_with_health / _scatter_search_batch /
    # _gather_merge (the scatter, owner-merge, failover, and hedge
    # spine) are inherited from ScatterReadPlane; only the three policy
    # hooks below are role-dependent.

    def _follower_active(self) -> bool:
        """Is the follower view usable for reads? Only once a payload
        has actually loaded — before the leader's first flush (or with
        persistence disabled) a non-leader keeps the legacy behavior
        rather than serving an empty view."""
        f = self.placement_follower
        return f is not None and f.loaded

    def _read_placement(self):
        """The placement view one read request routes under: the
        authoritative map while this node leads; the watch-refreshed
        follower view of the durable znode otherwise. The cached role
        is used (never an is_leader() coordination READ — this is the
        per-request hot path); transitions re-point the next request."""
        if self._role == "leader" or not self._follower_active():
            return self.placement
        return self.placement_follower

    # ---- shard recovery (SURVEY §5.3 — beyond the reference) ----

    def _store_path(self, name: str) -> str:
        """Resolve a name under the recovery store with the same
        traversal check as the engine's documents dir."""
        base = os.path.abspath(self._store_dir)
        target = os.path.abspath(os.path.join(base, name))
        if not (target == base or target.startswith(base + os.sep)):
            raise PermissionError(f"path escapes store dir: {name!r}")
        return target

    def _store_document(self, name: str, data: bytes) -> None:
        """Durable leader-side copy of a placed document (the recovery
        source; the reference's leader-local disk is already a download
        source, ``Leader.java:112-121``). Atomic + group-commit-fsynced
        through the durable-IO seam, with the CRC recorded in the scrub
        ledger. Best-effort: a failed store must not fail the upload it
        shadows (the replicas ARE durable — fsync-before-ack)."""
        try:
            path = self._store_path(name)
            storage.atomic_write_bytes(path, data,
                                       fsync=self.config.storage_fsync)
            self._store_ledger.record(name, zlib.crc32(data))
        except Exception as e:
            log.warning("leader document store write failed", file=name,
                        err=repr(e))

    def _store_read(self, name: str) -> bytes | None:
        """Read a stored placed document, verified against the scrub
        ledger when it has a record — a rotten recovery source must
        surface as MISSING (so recovery falls through to the replica
        download probe), never get re-placed as corrupt content."""
        try:
            path = self._store_path(name)
            if not os.path.isfile(path):
                return None
            data = storage.read_bytes(path)
            want = self._store_ledger.get(name)
            if want is not None:
                if zlib.crc32(data) != want:
                    global_metrics.inc("storage_corruptions_detected")
                    span_event("storage_corruption", file=name,
                               where="placed_docs")
                    log.warning("stored document failed CRC; treating "
                                "as missing", file=name)
                    return None
            return data
        except Exception:
            return None

    # ---- background integrity scrub (storage durability, README
    #      "Storage durability & integrity") ----

    def run_integrity_scrub(self) -> dict:
        """One scrub pass: verify every ledger-covered placed document
        against its recorded CRC, repairing a rotten local copy from a
        healthy replica through the same download probe the PR 5
        recovery uses; then verify the current checkpoint's manifest,
        quarantining a corrupt version (the next autosave re-creates
        it). Rides the leader's sweep loop (``storage_scrub_ms``);
        public so tests and operators can force a pass."""
        checked = repaired = unrepaired = 0
        for name in self._store_ledger.names():
            if self._stopping:
                break
            want = self._store_ledger.get(name)
            try:
                path = self._store_path(name)
            except PermissionError:
                continue
            if want is None or not os.path.isfile(path):
                continue
            checked += 1
            try:
                got = storage.file_crc(path)
            except OSError:
                got = None
            if got == want:
                continue
            # TOCTOU guard: a concurrent upsert may have rewritten the
            # file between the ledger read and the CRC — re-read the
            # ledger and skip if it moved (the NEXT pass judges the new
            # pair); without this, scrub could "repair" a just-acked
            # upsert back to its replica's OLD bytes or condemn a
            # perfectly valid new file
            if self._store_ledger.get(name) != want:
                continue
            # corroborate against a replica before judging (anti-
            # entropy: the workers holding the doc are the redundancy
            # this store backs). NOT leader_download — its locator
            # serves the local store first, which is exactly the copy
            # under suspicion.
            data = self._fetch_from_replicas(name)
            rcrc = zlib.crc32(data) if data is not None else None
            if rcrc is not None and got is not None and rcrc == got:
                # the replica agrees with the LOCAL FILE, not the
                # ledger: the ledger record is stale (a crash ate the
                # debounced flush after an acked upsert) — heal the
                # RECORD, never touch the healthy file
                self._store_ledger.record(name, got)
                global_metrics.inc("storage_scrub_ledger_heals")
                log.info("scrub healed stale ledger record (replica "
                         "corroborates the local file)", file=name)
                continue
            global_metrics.inc("storage_scrub_corruptions")
            span_event("storage_corruption", file=name,
                       where="placed_docs")
            if rcrc == want and self._store_ledger.get(name) == want:
                try:
                    storage.atomic_write_bytes(
                        path, data, fsync=self.config.storage_fsync)
                    repaired += 1
                    global_metrics.inc("storage_scrub_repairs")
                    log.info("scrub repaired rotten stored document "
                             "from a replica", file=name)
                    continue
                except OSError as e:
                    log.warning("scrub repair write failed", file=name,
                                err=repr(e))
            if self._store_ledger.get(name) != want:
                continue   # upsert landed mid-repair: next pass judges
            unrepaired += 1
            global_metrics.inc("storage_scrub_unrepaired")
            # deliberately NON-destructive: ledger-vs-file disagreement
            # with no replica corroboration either way could be a
            # rotten file OR a healthy upsert whose ledger flush a
            # crash ate — destroying the bytes on that evidence could
            # delete the only leader copy of an acked write. The pair
            # stays on disk, loudly recounted each pass; _store_read
            # keeps refusing the mismatch, so the suspect bytes are
            # never served as a recovery source either way.
            log.warning("scrub found ledger/file CRC disagreement with "
                        "no replica corroboration; leaving both in "
                        "place (recovery falls back to the download "
                        "probe)", file=name)
        self._store_ledger.flush(fsync=self.config.storage_fsync)
        # checkpoint integrity: a corrupt CURRENT version is quarantined
        # now, while the fallback version still exists — not discovered
        # at the next boot, when the re-walk bill comes due
        ckpt_bad = 0
        from tfidf_tpu.engine.checkpoint import (checkpoint_versions,
                                                 quarantine_version)
        for vdir in checkpoint_versions(self.checkpoint_dir):
            problems = storage.verify_manifest(vdir)
            if problems and all("manifest missing" in p
                                for p in problems):
                # pre-manifest legacy version: unverifiable, not
                # corrupt — restore_checkpoint keeps it loadable as a
                # last resort, so the scrub must not destroy it (the
                # next save supersedes it with a manifested one)
                continue
            if problems:
                ckpt_bad += 1
                span_event("storage_corruption",
                           file=os.path.basename(vdir),
                           where="checkpoint")
                log.warning("scrub found corrupt checkpoint version",
                            dir=vdir, problems=problems[:3])
                quarantine_version(vdir)
        global_metrics.inc("storage_scrub_passes")
        out = {"checked": checked, "repaired": repaired,
               "unrepaired": unrepaired, "checkpoints_quarantined":
               ckpt_bad}
        if repaired or unrepaired or ckpt_bad:
            log.info("integrity scrub pass", **out)
        return out

    def _fetch_from_replicas(self, name: str) -> bytes | None:
        """Fetch a document's bytes from the worker fleet ONLY (never
        the local durable store — the scrub calls this exactly when the
        local copy is the rotten one). Same probe discipline as
        ``leader_download_stream``'s worker loop."""
        q = urllib.parse.quote(name)
        for w in self.registry.get_all_service_addresses():
            if self.resilience.board.is_open(w):
                continue
            try:
                resp = self.resilience.worker_call(
                    w, lambda w=w: http_get_stream(
                        w + f"/worker/download?path={q}", timeout=30.0,
                        origin=self.url),
                    retry=False)
                try:
                    return resp.read()
                finally:
                    resp.close()
            except Exception:
                continue
        return None

    def _on_membership_change(self, old, new) -> None:
        """Registry watch hook (watch-dispatch thread — hand off fast).

        The leader check happens in the SPAWNED thread, not here: it is
        a coordination read (an RPC on the HTTP transport, up to the
        client's failover deadline), and this hook runs under the
        registry's notify lock on the shared watch-dispatch thread — a
        stalled leader check here would delay every other client
        event, including the election NodeDeleted that failover
        latency depends on (graftcheck lockgraph finding)."""
        # membership epoch: scatter batches formed before and after
        # this transition never share a coalesced group (the batcher's
        # submit-time group key)
        self._cluster_epoch += 1
        if self._stopping or not self.config.shard_recovery:
            return
        lost = set(old) - set(new)
        joined = set(new) - set(old)
        if lost or joined:
            threading.Thread(
                target=self._reconcile_if_leader, args=(lost, joined),
                daemon=True, name=f"shard-recovery-{self.port}").start()

    def _reconcile_if_leader(self, lost: set[str],
                             joined: set[str]) -> None:
        """Off-dispatch-thread half of the membership hook: the same
        leader gate the hook used to apply inline (is_leader is
        recomputed from live children either way, so the check was
        always racy-by-design against a concurrent re-election)."""
        if self._stopping or not self.is_leader():
            return
        self._reconcile_membership(lost, joined)

    def _reconcile_membership(self, lost: set[str],
                              joined: set[str]) -> None:
        """Re-place a dead worker's documents onto survivors (from the
        leader's durable store), and delete moved documents from a
        rejoining worker so the corpus stays single-copy.

        The reference's recovery is pod-restart + re-walk, during which
        the shard is simply unsearchable (``Worker.java:77-94``,
        ``ServiceRegistry.java:91-122``); this closes that gap for every
        document placed during the current leader's tenure.

        Reconciles run ONE AT A TIME (``_reconcile_serial``) in event
        order, so a rejoin never interleaves with an in-flight recovery;
        a recovery additionally aborts as soon as the lost worker
        reappears in the registry (the rejoiner's boot re-walk serves
        whatever was not yet re-placed), and a name only ever enters
        ``_moved`` after its confirmed placement is a DIFFERENT worker —
        deleting the sole copy is impossible by construction. The
        replication-repair pass that follows a death takes the same
        serial lock itself, so it runs AFTER this block releases it."""
        with self._reconcile_serial:
            for w in joined:
                self._reconcile_rejoined(w)
            for w in lost:
                self._recover_lost_worker(w)
        if lost and self.config.shard_recovery:
            # restore R for documents that survived on replicas (runs
            # outside the block above; repair re-acquires the serial
            # lock so it can never interleave with a reconcile delete)
            try:
                self.run_replication_repair()
            except Exception as e:
                log.warning("post-death replication repair failed",
                            err=repr(e))

    def _reconcile_rejoined(self, w: str) -> bool:
        """Delete this rejoiner's moved documents from it (one retried,
        breaker-gated RPC). The names stay in ``_moved`` — and therefore
        excluded from ``w``'s merged results — until the worker CONFIRMS
        the deletes; popping them up front would open a double-count
        window for every search that races the RPC (the transient
        variant of the ADVICE r5 finding). On failure the sweep (and any
        next join event) retries. Caller holds ``_reconcile_serial``."""
        with self._placement_lock:
            moved = set(self._moved.get(w, ()))
        if not moved:
            return True

        def rpc() -> dict:
            global_injector.check("leader.reconcile_rpc")
            return json.loads(http_post(
                w + "/worker/delete",
                json.dumps({"names": sorted(moved)}).encode(),
                timeout=120.0, headers=self._epoch_headers(),
                origin=self.url))

        try:
            resp = self._worker_call_fenced(w, rpc)
        except Exception as e:
            global_metrics.inc("reconcile_failures")
            log.warning("rejoin reconciliation failed", worker=w,
                        err=repr(e))
            return False
        # names moved DURING the RPC stay pending
        self.placement.moved_resolved(w, moved)
        # the confirmed deletes changed that worker's df — invalidate
        self.bump_result_generation()
        global_metrics.inc("reconciles_completed")
        log.info("reconciled rejoined worker", worker=w,
                 deleted=resp.get("deleted", 0))
        return True

    def _reconcile_sweep_loop(self) -> None:
        """Leader-side anti-entropy loop: retries failed rejoin
        reconciles (ADVICE r5 medium: without it a failed
        /worker/delete leaves moved documents double-indexed until the
        NEXT membership change) AND repairs the replication factor —
        re-replicating under-replicated documents after a death,
        trimming over-replication after a rejoin. Runs on every node;
        does work only while leader."""
        interval = self.config.reconcile_sweep_interval_s
        while not self._stopping:
            time.sleep(interval)
            if self._stopping:
                return
            try:
                # is_leader() can itself raise in the window where a
                # session-expiry rejoin has rebuilt the election but not
                # yet re-volunteered — a sweep thread must survive every
                # transient, or reconciles stop retrying forever
                if not self.is_leader():
                    continue
                self.run_reconcile_sweep()
                self.run_replication_repair()
                # elastic rebalance rides the same leader-side loop,
                # self-paced by rebalance_sweep_ms
                self.rebalancer.maybe_run()
                # SLO autopilot control pass (cluster/autopilot.py),
                # self-paced by autopilot_interval_ms
                self.autopilot.maybe_run()
                # residue anti-entropy (ghost/orphan reconciliation),
                # self-paced by residue_sweep_ms
                now = time.monotonic()
                if (self.config.residue_sweep_ms >= 0
                        and now - self._residue_last
                        >= self.config.residue_sweep_ms / 1e3):
                    self._residue_last = now
                    self.run_residue_reconcile()
                # background integrity scrub (storage durability),
                # self-paced by storage_scrub_ms
                if (self.config.storage_scrub_ms >= 0
                        and now - self._scrub_last
                        >= self.config.storage_scrub_ms / 1e3):
                    self._scrub_last = now
                    self.run_integrity_scrub()
            except Exception as e:
                log.warning("reconcile sweep pass failed", err=repr(e))

    def run_reconcile_sweep(self) -> int:
        """One sweep pass: retry the pending reconcile of every worker
        that is currently live (a still-dead worker has nothing indexed
        to delete; its join event or a later pass will catch it).
        Returns the number of workers converged. Public so tests and
        operators can force a pass without waiting for the timer."""
        with self._placement_lock:
            pending = [w for w, ns in self._moved.items() if ns]
        if not pending:
            return 0
        global_injector.check("leader.sweep")
        global_metrics.inc("reconcile_sweeps")
        live = set(self.registry.get_all_service_addresses())
        done = 0
        for w in pending:
            if w not in live or self._stopping:
                continue
            global_metrics.inc("reconcile_sweep_retries")
            with self._reconcile_serial:
                if self._reconcile_rejoined(w):
                    done += 1
        return done

    def _recover_lost_worker(self, w: str) -> None:
        """Handle a worker's death. Documents with surviving replicas
        stay searchable THROUGH the failover scatter path the moment
        the owner assignment recomputes — they only need their
        replication factor restored (the repair pass below). Documents
        whose LAST replica died are re-placed urgently from the durable
        store, exactly the single-copy recovery of old."""
        kept, lost = self.placement.drop_worker(w)
        if not kept and not lost:
            return
        if kept:
            log.info("worker lost; surviving replicas keep its shard "
                     "searchable", worker=w, docs=len(kept))
        replaced = 0
        missing = 0
        batch: list[dict] = []
        aborted = False
        if lost:
            log.info("re-placing lost worker's shard", worker=w,
                     docs=len(lost))
        for name in lost:
            if w in self.registry.get_all_service_addresses():
                # the worker came back mid-recovery: stop — its boot
                # re-walk serves everything not yet re-placed, and the
                # rejoin reconcile (queued behind this one) deletes
                # what was
                aborted = True
                break
            data = self._store_read(name)
            if data is None:
                # placed before this leader's tenure (or its store
                # write failed): the download probe still covers the
                # promoted-ex-worker case (the new leader's own docs
                # dir holds the shard it served before its promotion
                # removed it from the worker pool)
                try:
                    data = self.leader_download(name)
                except Exception:
                    data = None
            if data is None:
                # no byte source anywhere — count and surface: these
                # stay dark until the pod restarts, exactly the
                # reference's behavior
                missing += 1
                continue
            try:
                text = data.decode("utf-8")
                batch.append({"name": name, "text": text})
                if len(batch) >= 500:
                    replaced += self._replace_batch(batch, w)
                    batch = []
                continue
            except UnicodeDecodeError:
                pass
            try:   # non-UTF-8 (binary-extractable) docs: per-file
                self.leader_upload(name, data)
                replaced += self._note_moved([name], w)
            except Exception as e:
                log.warning("re-placement failed", file=name,
                            err=repr(e))
        if batch:
            replaced += self._replace_batch(batch, w)
        global_metrics.inc("shard_recoveries")
        global_metrics.inc("shard_docs_replaced", replaced)
        if missing:
            global_metrics.inc("shard_docs_unrecovered", missing)
            log.warning("shard recovery left documents dark (no durable "
                        "copy; placed before this leader's tenure)",
                        worker=w, unrecovered=missing)
        log.info("shard recovery complete", worker=w, replaced=replaced,
                 survived=len(kept), known=len(kept) + len(lost),
                 missing=missing, aborted=aborted)

    def _note_moved(self, names: list[str], old_worker: str) -> int:
        """Record names as moved away from ``old_worker`` — only those
        whose CONFIRMED replica set now excludes it (a doc the upload
        routed back onto a just-rejoined ``old_worker`` must not be
        scheduled for deletion from it)."""
        return self.placement.note_moved(names, old_worker)

    def _replace_batch(self, docs: list[dict], old_worker: str) -> int:
        try:
            resp = self.leader_upload_batch(docs)
        except Exception as e:
            log.warning("re-placement batch failed", err=repr(e),
                        docs=len(docs))
            return 0
        # only names a worker ACCEPTED count as moved: 'skipped' are
        # media-type rejections, 'failed' are transport-errored groups
        # that were never indexed anywhere
        not_placed = {s["name"] for s in resp.get("skipped", ())}
        not_placed.update(resp.get("failed", ()))
        return self._note_moved(
            [d["name"] for d in docs if d["name"] not in not_placed],
            old_worker)

    # ---- anti-entropy replication repair ----

    def run_replication_repair(self) -> dict:
        """One anti-entropy pass (generalizing the reconcile sweep):
        restore the replication factor for under-replicated documents
        (new copies from the durable store onto the least-loaded live
        workers not already holding them) and trim over-replication
        after rejoins (extras are scheduled for deletion through the
        same pending-reconcile machinery as moves). Public so tests and
        operators can force a pass without waiting for the timer.

        Serialized with the reconcile machinery (``_reconcile_serial``,
        taken here — callers must not hold it): a repair must never
        re-add a copy to a worker while a reconcile delete for that
        same name is on the wire, or the delete lands after the re-add
        and silently erases a mapped replica."""
        if self._stopping or not self.config.shard_recovery:
            return {}
        live = set(self.registry.get_all_service_addresses())
        if not live:
            return {}
        global_injector.check("leader.repair")
        with self._reconcile_serial:
            return self._repair_pass(live)

    def _repair_pass(self, live: set[str]) -> dict:
        """Body of :meth:`run_replication_repair`; caller holds
        ``_reconcile_serial`` (never the placement lock)."""
        r = max(1, min(self.config.replication_factor, len(live)))
        under = self.placement.under_replicated(live, r)
        added = repaired_missing = 0
        draining = self.placement.draining_snapshot()
        if under:
            global_metrics.inc("repair_passes")
            # never repair ONTO a draining worker — its drain would just
            # migrate the fresh copy straight back off
            targets_pool = [w for w in live
                            if not self.resilience.board.is_open(w)
                            and w not in draining]
            try:
                self._ensure_sizes_fresh(targets_pool or sorted(live))
            except Exception as e:
                log.warning("repair size poll failed", err=repr(e))
                return {}
            with self._placement_lock:
                sizes = dict(self._size_cache[1])
            assignments: dict[str, list[str]] = {}
            for name, reps in sorted(under.items()):
                # _load_doc_bytes covers the new-leader case (no store
                # of its own for a predecessor's placements: download
                # probe + cache back into the store)
                data = self._load_doc_bytes(name)
                if data is None:
                    repaired_missing += 1
                    continue
                cands = sorted(
                    (w for w in live
                     if w not in reps and w in sizes
                     and w not in draining
                     and not self.resilience.board.is_open(w)),
                    key=lambda w: (sizes[w], w))
                for target in cands[:r - len(reps)]:
                    sizes[target] = sizes.get(target, 0) + len(data)
                    assignments.setdefault(name, []).append(target)
            added += self._replicate_to_targets(assignments)
            if added:
                global_metrics.inc("repair_docs_replicated", added)
        trimmed = self.placement.trim_plan(live, r)
        n_trim = sum(len(ns) for ns in trimmed.values())
        if n_trim:
            # the actual deletes ride the reconcile sweep/rejoin path
            global_metrics.inc("repair_docs_trimmed", n_trim)
            log.info("scheduled over-replication trim",
                     docs=n_trim, workers=len(trimmed))
        if repaired_missing:
            global_metrics.inc("repair_docs_unrecoverable",
                               repaired_missing)
        return {"replicated": added, "trimmed": n_trim,
                "missing": repaired_missing}

    def run_residue_reconcile(self) -> dict:
        """Anti-entropy for UNMAPPED engine residue — the partition
        leftovers owner assignment can only mask, never clean. Each
        live worker reports the names its engine ACTUALLY serves
        (``GET /worker/names``); copies the placement map does not
        credit are either GHOSTS (mapped elsewhere / pending deletion:
        scheduled away through the moved machinery — they silently
        skew that shard's df/N statistics and resurface the moment the
        name leaves the map) or ORPHANS (mapped nowhere: a write that
        landed but whose placement was lost to a partition — adopted
        as a first-class confirmed replica, then R-restored by the
        repair pass). Public so tests and operators can force a pass;
        self-paced in the sweep loop by ``residue_sweep_ms``."""
        if self._stopping or not self.config.shard_recovery:
            return {}
        live = set(self.registry.get_all_service_addresses())
        if not live:
            return {}
        protected = self.placement.migrating_names()
        ghosts = orphans = 0
        with self._reconcile_serial:
            for w in sorted(live):
                if self.resilience.board.is_open(w) or self._stopping:
                    continue
                try:
                    payload = json.loads(self.resilience.worker_call(
                        w, lambda w=w: http_get(
                            w + "/worker/names", origin=self.url),
                        retry=False))
                except Exception as e:
                    log.warning("residue name fetch failed", worker=w,
                                err=repr(e))
                    continue
                names = payload.get("names")
                if not names:
                    continue   # empty engine, or a layout that can't list
                g, o = self.placement.reconcile_residue(
                    w, [str(n) for n in names], protected)
                ghosts += len(g)
                orphans += len(o)
                if g or o:
                    log.info("residue reconciled", worker=w,
                             ghosts=len(g), orphans_adopted=len(o))
            # the leader's OWN engine (an ex-worker's shard) can hold
            # the ONLY copy of an orphan — it serves no scatter, so an
            # unmapped doc here is unreachable until re-placed through
            # the normal upload path
            own = self.engine.document_names() or ()
            replaced = 0
            for name in self.placement.unplaced_of(
                    [str(n) for n in own], protected):
                if self._stopping:
                    break
                got = None
                try:
                    got = self.engine.open_document_stream(name)
                except Exception:
                    got = None
                if got is None:
                    continue
                stream, _sz = got
                try:
                    data = stream.read()
                finally:
                    stream.close()
                try:
                    self.leader_upload(name, data)
                    replaced += 1
                except Exception as e:
                    log.warning("residue re-place from own engine "
                                "failed", file=name, err=repr(e))
            if replaced:
                global_metrics.inc("residue_leader_replaced", replaced)
                log.info("re-placed orphans from the leader's own "
                         "engine", docs=replaced)
        if ghosts:
            global_metrics.inc("residue_ghosts", ghosts)
        if orphans:
            global_metrics.inc("residue_orphans_adopted", orphans)
            # adopted orphans change which shard scores those names
            self.bump_result_generation()
        global_metrics.inc("residue_sweeps")
        return {"ghosts": ghosts, "orphans": orphans}

    def _load_doc_bytes(self, name: str) -> bytes | None:
        """Byte source for replica/migration copies: the leader's
        durable store first, else the download probe (its own engine
        dir, then surviving replicas), caching probe hits back into
        the store so future copies are store-local."""
        data = self._store_read(name)
        if data is not None:
            return data
        try:
            data = self.leader_download(name)
        except Exception:
            data = None
        if data is not None:
            self._store_document(name, data)
        return data

    def _replicate_to_targets(self,
                              assignments: dict[str, list[str]]) -> int:
        """Fan NEW replica copies out to their assigned workers — text
        docs grouped into one upload-batch per worker, binary docs
        per-file — recording accepted copies in the placement map.
        Shared by the anti-entropy repair pass and the rebalancer's
        migration copy phase. Returns the number of confirmed legs."""
        batches: dict[str, list[dict]] = {}
        files: dict[str, list[tuple[str, bytes]]] = {}
        for name, targets in assignments.items():
            data = self._load_doc_bytes(name)
            if data is None:
                log.warning("no byte source for replica copy; leaving "
                            "the doc where it is", file=name)
                continue
            for target in targets:
                try:
                    batches.setdefault(target, []).append(
                        {"name": name, "text": data.decode("utf-8")})
                except UnicodeDecodeError:
                    files.setdefault(target, []).append((name, data))
        n = 0
        for target, docs in batches.items():
            n += self._add_replica_batch(target, docs)
        for target, items in files.items():
            for name, data in items:
                n += self._add_replica_file(target, name, data)
        return n

    def _add_replica_batch(self, target: str, docs: list[dict]) -> int:
        """Forward one upload-batch of NEW replica copies to ``target``
        and record the accepted ones in the placement map."""
        try:
            resp = json.loads(self._worker_call_fenced(
                target, lambda: http_post(
                    target + "/worker/upload-batch",
                    json.dumps(docs).encode(), timeout=300.0,
                    headers=self._epoch_headers(), origin=self.url)))
        except Exception as e:
            log.warning("replica repair batch failed", worker=target,
                        docs=len(docs), err=repr(e))
            return 0
        skipped = {s["name"] for s in resp.get("skipped", ())}
        n = 0
        for d in docs:
            if d["name"] in skipped:
                continue
            if self.placement.add_replica(d["name"], target):
                n += 1
            else:
                # a client delete won the race against this copy leg:
                # the landed bytes are a stray — schedule them away
                self.placement.note_stray(d["name"], target)
        return n

    def _add_replica_file(self, target: str, name: str,
                          data: bytes) -> int:
        q = urllib.parse.quote(name)
        try:
            self._worker_call_fenced(
                target, lambda: http_post(
                    target + f"/worker/upload?name={q}", data,
                    content_type="application/octet-stream",
                    headers=self._epoch_headers(), origin=self.url))
        except Exception as e:
            log.warning("replica repair upload failed", worker=target,
                        file=name, err=repr(e))
            return 0
        if not self.placement.add_replica(name, target):
            self.placement.note_stray(name, target)   # deleted mid-copy
            return 0
        return 1

    # size polls are cached this long; between polls the leader grows
    # its local estimates by the bytes it placed, so bursts still spread
    _SIZE_POLL_TTL_S = 1.0

    def _ensure_sizes_fresh(self, workers: list[str]) -> None:
        """Refresh the worker index-size TTL cache (the per-upload
        polling loop of ``Leader.java:170-179``). Raises when no worker
        answers. The serial HTTP polls run OUTSIDE ``_placement_lock`` —
        one slow/unreachable worker must not stall every concurrent
        upload handler for the poll timeout; only the freshness check
        and the install are under the lock."""
        now = time.monotonic()
        with self._placement_lock:
            # prune stale eviction records (only recent ones can race a
            # poll in flight; polls take at most the HTTP timeout)
            for w, e in list(self._evicted.items()):
                if now - e > 60.0:
                    del self._evicted[w]
            ts, sizes = self._size_cache
            if (now - ts <= self._SIZE_POLL_TTL_S
                    and set(sizes) == set(workers)):
                return
        polled = {}
        for w in workers:   # serial polling, like Leader.java:170-179
            if self.resilience.board.is_open(w):
                continue   # don't pay the poll timeout for a sick worker
            try:
                def poll(w=w) -> int:
                    global_injector.check("leader.size_poll")
                    return int(http_get(w + "/worker/index-size",
                                        origin=self.url))
                # breaker-tracked, no retry: the TTL cache re-polls soon
                # anyway, and failed polls feed the breaker so repeat
                # offenders drop out of the serial loop above
                polled[w] = self.resilience.worker_call(w, poll,
                                                        retry=False)
            except Exception as e:
                log.warning("index-size poll failed", worker=w,
                            err=repr(e))
        if not polled:
            raise RuntimeError("no reachable workers")
        with self._placement_lock:
            # drop poll results that predate a concurrent eviction: the
            # worker answered our poll, then failed an upload — keeping
            # its pre-failure size would resurrect a dead worker into
            # the cache and route uploads at it until the next TTL
            polled = {w: v for w, v in polled.items()
                      if self._evicted.get(w, -1.0) <= now}
            ts2, cur = self._size_cache
            if ts2 <= ts:   # no fresher concurrent poll landed meanwhile
                if polled:
                    self._size_cache = (now, polled)
            else:
                # a concurrent poll won the install; MERGE our results in
                # for workers it did not cover (its registry view may
                # differ from ours) so this caller's worker set is still
                # represented — discarding our poll could leave the
                # cache empty for our workers and 500 a healthy upload
                self._size_cache = (ts2, {**polled, **cur})

    def _leg_succeeded(self, name: str, worker: str,
                       nbytes: int) -> None:
        """One upload leg accepted: confirm the placement leg and bump
        the local size estimate (only for workers already present in
        the cache: re-inserting an evicted/unpolled worker at near-zero
        size would defeat the set-mismatch re-poll signal and min-route
        every new name onto it until TTL expiry)."""
        # a confirmed copy changed that worker's shard (and its df) —
        # cached query results stamped before this commit must die
        self.bump_result_generation()
        self.placement.leg_success(name, worker)
        with self._placement_lock:
            sizes = self._size_cache[1]
            if worker in sizes:
                sizes[worker] += nbytes

    def _leg_failed(self, name: str, worker: str,
                    app_reject: bool) -> None:
        """One upload leg failed: release the never-confirmed tentative
        replica (phantom cleanup lives in the placement map) and, for
        transport failures, evict the worker from the size cache so the
        next upload re-polls at once instead of re-choosing the dead
        worker until TTL expiry. A 4xx is an APPLICATION rejection from
        a healthy worker — no eviction, or interleaved bad uploads
        would force a full serial re-poll before every good one."""
        self.placement.leg_failure(name, worker)
        if not app_reject:
            with self._placement_lock:
                self._size_cache[1].pop(worker, None)
                self._evicted[worker] = time.monotonic()

    def leader_upload(self, filename: str, data: bytes) -> dict:
        """R-way least-loaded placement (generalizing
        ``Leader.java:153-207``):

        * worker index sizes are polled at most once per TTL (the
          reference polls every worker for every file,
          ``Leader.java:170-179`` — O(workers) HTTP round trips per
          document kills bulk ingest);
        * a NEW name fans out to ``replication_factor`` distinct
          least-loaded workers (capped by the live worker count); a
          name seen before routes to the workers already holding it,
          so a re-upload UPSERTS every existing copy instead of
          placing duplicates (which would diverge replicas and
          double-count in a naive merge). The map is durable through
          the coordination substrate, so holders survive leader
          failover.

        The upload succeeds when AT LEAST ONE replica accepted (the
        document is searchable); a failed leg's tentative replica is
        released and the anti-entropy repair loop restores the
        replication factor from the durable store."""
        workers = self.registry.get_all_service_addresses()
        if not workers:
            raise RuntimeError("no workers registered")
        # route NEW names away from workers with open breakers and from
        # DRAINING workers (held names still go to their holders —
        # replica continuity beats liveness, and an upsert must hit the
        # current copies even mid-drain); if every candidate is
        # excluded, fall through and let the call fail honestly rather
        # than refuse on stale breaker/drain state
        draining = self.placement.draining_snapshot()
        route_workers = (
            [w for w in workers if not self.resilience.board.is_open(w)
             and w not in draining]
            or [w for w in workers if w not in draining]
            or workers)
        with self._placement_lock:
            held = tuple(w for w in self.placement.replicas.get(
                filename, ()) if w in workers)
        if not held:
            self._ensure_sizes_fresh(route_workers)  # polls off the lock
        with self._placement_lock:
            replicas, _new = self.placement.route_locked(
                filename, workers, self._size_cache[1], route_workers,
                self.config.replication_factor)
        q = urllib.parse.quote(filename)

        def send(w: str):
            # retried (bounded) on transient transport failures: the
            # worker-side ingest is an idempotent upsert by name, so a
            # double-applied attempt converges to the same index state.
            # Epoch-stamped and fence-aware: a 403 fence rejection
            # means a newer leader exists — step down, never retry.
            return self._worker_call_fenced(
                w, lambda w=w: http_post(
                    w + f"/worker/upload?name={q}", data,
                    content_type="application/octet-stream",
                    headers=self._epoch_headers(), origin=self.url))

        futs = {self._pool.submit(send, w): w for w in replicas}
        confirmed: list[str] = []
        errors: dict[str, BaseException] = {}
        for fut, w in futs.items():
            try:
                try:
                    # bounded: ~attempts x the 30s http timeout + backoff
                    fut.result(timeout=120.0)
                except FutureTimeout:
                    # the shared pool may have QUEUED this leg behind
                    # slow scatters — only a cancelled (never-started)
                    # leg is truly failed; a running one is bounded by
                    # its own RPC timeouts and must be awaited, or a
                    # worker that eventually ACCEPTED the copy would be
                    # recorded as not holding it (unmapped duplicate =
                    # double count)
                    if fut.cancel():
                        raise
                    fut.result(timeout=900.0)
            except BaseException as e:
                errors[w] = e
                self._leg_failed(
                    filename, w,
                    app_reject=(isinstance(e, urllib.error.HTTPError)
                                and e.code < 500))
                continue
            confirmed.append(w)
            self._leg_succeeded(filename, w, len(data))
        if not confirmed:
            # every replica failed: propagate one error (an application
            # rejection — e.g. 415 — wins so the handler's status
            # mapping stays intact; all replicas see the same bytes)
            for e in errors.values():
                if isinstance(e, urllib.error.HTTPError) and e.code < 500:
                    raise e
            raise next(iter(errors.values()))
        if len(confirmed) < len(replicas):
            global_metrics.inc("uploads_partially_replicated")
        if self.config.shard_recovery:
            self._store_document(filename, data)
        global_metrics.inc("uploads_placed")
        with self._placement_lock:
            sizes = dict(self._size_cache[1])
        # the worker may be absent from the size cache (held-route after
        # an eviction skips the freshness poll) — never KeyError a
        # SUCCESSFUL upload on a logging detail
        log.info("upload placed", file=filename, workers=confirmed,
                 size=sizes.get(confirmed[0], -1))
        return {"worker": confirmed[0], "replicas": confirmed,
                "sizes": sizes}

    def leader_upload_batch(self, docs: list[dict]) -> dict:
        """Bulk ingest (framework addition — the reference only places
        one file per request): place each named document on its
        ``replication_factor`` least-loaded workers with the same
        cached policy as the per-file path, then forward ONE
        ``upload-batch`` request per worker (a document appears in R
        workers' groups). Payloads are JSON ``{"name", "text"}`` (text
        documents; binary uploads use the per-file endpoint).

        ``placed`` counts per-worker ACCEPTED copies; ``failed`` lists
        names no worker confirmed (transport-errored on every replica
        leg) — a partially-replicated name is placed (searchable) and
        the repair loop restores its missing copies later."""
        workers = self.registry.get_all_service_addresses()
        if not workers:
            raise RuntimeError("no workers registered")
        # same open-breaker + draining routing rule as the per-file path
        draining = self.placement.draining_snapshot()
        route_workers = (
            [w for w in workers if not self.resilience.board.is_open(w)
             and w not in draining]
            or [w for w in workers if w not in draining]
            or workers)
        # validate BEFORE any tracking: a KeyError mid-planning-loop
        # would leak in-flight legs for docs already routed, pinning
        # those names to never-confirmed placements forever
        for d in docs:
            if not isinstance(d, dict) or not isinstance(
                    d.get("name"), str) or not d["name"]:
                raise ValueError("every document needs a string 'name'")
            if not isinstance(d.get("text", ""), str):
                raise ValueError("document 'text' must be a string")
        # plan the split with a local estimate; placement confirmations
        # happen only for copies a worker ACCEPTED — a failed forward
        # must not leave the leader believing the unreachable worker
        # holds documents it never received. New names claim their R
        # replicas under the lock so a concurrent upload of the same
        # name routes to the same workers.
        self._ensure_sizes_fresh(route_workers)   # polls outside the lock
        per_worker: dict[str, list[dict]] = {}
        with self._placement_lock:
            # plan against a local estimate so the batch itself spreads
            # by projected size; claims/placements go through the same
            # routing rule as the per-file path
            est = {w: self._size_cache[1][w] for w in route_workers
                   if w in self._size_cache[1]}
            for d in docs:
                name = d["name"]
                reps, _new = self.placement.route_locked(
                    name, workers, est, route_workers,
                    self.config.replication_factor)
                for w in reps:
                    per_worker.setdefault(w, []).append(d)
                    # bump only workers already in the estimate: a held
                    # name routed to an unpolled worker must not inject
                    # it at near-zero size, or every later NEW name in
                    # the batch would min-route onto the
                    # possibly-unreachable worker
                    if w in est:
                        est[w] += len(d.get("text", ""))

        def forward(w: str, group: list[dict]) -> dict:
            # bounded transient retry; worker-side ingest is an
            # idempotent upsert by name (see leader_upload).
            # Epoch-stamped + fence-aware like every mutating RPC.
            return json.loads(self._worker_call_fenced(
                w, lambda: http_post(
                    w + "/worker/upload-batch",
                    json.dumps(group).encode(), timeout=300.0,
                    headers=self._epoch_headers(), origin=self.url)))

        futs = {self._pool.submit(forward, w, group): (w, group)
                for w, group in per_worker.items()}
        placed = {}
        errors = {}
        full_disk_errors: dict[str, Exception] = {}
        skipped_by_name: dict[str, dict] = {}
        confirmed_names: set[str] = set()
        for fut, (w, group) in futs.items():
            try:
                try:
                    # bounded: ~attempts x the 300s http timeout
                    resp = fut.result(timeout=1200.0)
                except FutureTimeout:
                    # same queued-vs-running distinction as the
                    # per-file path: never fail a leg that may still
                    # land on the worker
                    if fut.cancel():
                        raise
                    resp = fut.result(timeout=1200.0)
            except Exception as e:
                errors[w] = repr(e)
                # a 507 (disk full) is an app-level verdict from a
                # healthy, reachable worker: never evict its size
                # cache (a transport-failure remedy), and remember the
                # exception so an all-full-disks batch relays 507
                # instead of a retryable 500
                if isinstance(e, urllib.error.HTTPError) \
                        and e.code == storage.STORAGE_FULL_STATUS:
                    full_disk_errors[w] = e
                app_reject = (isinstance(e, urllib.error.HTTPError)
                              and (e.code < 500 or e.code
                                   == storage.STORAGE_FULL_STATUS))
                for d in group:   # settle EVERY leg, claimed or held
                    self.placement.leg_failure(d["name"], w)
                if not app_reject:      # fast re-poll on transport
                    with self._placement_lock:   # failures only
                        self._size_cache[1].pop(w, None)
                        self._evicted[w] = time.monotonic()
                continue
            # the worker reports per-doc UnsupportedMediaType skips —
            # those names were NOT indexed and must not enter the
            # placement map or the placed counts
            w_skipped = {s["name"] for s in resp.get("skipped", ())}
            for s in resp.get("skipped", ()):
                skipped_by_name.setdefault(s["name"], s)
            placed[w] = len(group) - len(w_skipped)
            for d in group:
                name = d["name"]
                if name in w_skipped:
                    self.placement.leg_failure(name, w)
                    continue
                self._leg_succeeded(name, w, len(d.get("text", "")))
                confirmed_names.add(name)
        if self.config.shard_recovery:
            for d in docs:
                if d["name"] in confirmed_names:
                    self._store_document(
                        d["name"], d.get("text", "").encode("utf-8"))
        global_metrics.inc("uploads_placed", len(confirmed_names))
        if errors and not placed:
            if len(full_disk_errors) == len(errors):
                # every leg answered 507: relay the distinct disk-full
                # verdict (non-retryable, never a breaker trip) rather
                # than a generic 500 the client would classify as a
                # retryable worker fault
                raise next(iter(full_disk_errors.values()))
            raise RuntimeError(f"all workers failed: {errors}")
        out = {"placed": placed}
        if skipped_by_name:
            out["skipped"] = list(skipped_by_name.values())
        if errors:
            out["errors"] = errors
            # names no replica confirmed and no worker skipped: never
            # indexed anywhere
            out["failed"] = [d["name"] for d in docs
                             if d["name"] not in confirmed_names
                             and d["name"] not in skipped_by_name]
        return out

    def leader_delete(self, names: list[str]) -> dict:
        """Cluster-wide document deletion (framework addition — the
        reference cannot delete a placed document at all; the jepsen
        partition workload needs a client-driven delete leg).

        Ordering makes the ack honest under crashes and partitions:

        1. the names leave the placement map and their copies enter
           the pending-reconcile (``moved``) machinery — merged search
           results exclude them IMMEDIATELY, before any worker RPC;
        2. the removal is made durable (synchronous placement flush —
           a flush failure fails the request, so an acked delete can
           never resurrect on a new leader);
        3. the leader's durable byte copy is dropped (repair can no
           longer re-place it — it already cannot, the map entry is
           gone, but the store must not outlive the doc);
        4. the worker-side deletes are pushed now (fenced, epoch-
           stamped); any failed leg is retried by the reconcile sweep
           — the pending exclusion keeps results exact meanwhile."""
        names = [str(n) for n in names]
        live = set(self.registry.get_all_service_addresses())
        # blanket-schedule across every LIVE worker, not just mapped
        # holders: a ghost copy (an upload leg recorded failed whose
        # request the worker actually processed) is masked by owner
        # assignment only while the name is mapped — the delete must
        # hunt it down everywhere or it resurrects unmapped
        scheduled = self.placement.forget(names, also=live)
        # invalidate cached results NOW — the map already excludes the
        # names, so a cache hit serving them would disagree with every
        # fresh scatter (and the fenced push loop below can stall for
        # seconds against a partitioned worker)
        self.bump_result_generation()
        if scheduled and not self._delete_flush_ok():
            raise RuntimeError(
                "delete not acknowledged: placement removal could not "
                "be made durable (the doc is gone from THIS leader's "
                "results, but a failover could resurrect it)")
        for n in names:
            try:
                path = self._store_path(n)
                if os.path.isfile(path):
                    os.remove(path)
            except Exception as e:
                log.warning("durable store cleanup failed", file=n,
                            err=repr(e))
            # purge the leader's OWN engine copy too (an ex-worker's
            # shard, or the dual-role single-node case): the residue
            # pass re-places own-engine orphans, so a lingering local
            # copy of a deleted doc would resurrect through it
            try:
                if self.engine.remove_document(n):
                    self.notify_write()
            except Exception:
                pass
        deleted = 0
        for w, ns in scheduled.items():
            if w not in live:
                continue   # sweep/rejoin reconcile owns it later

            def rpc(w=w, ns=ns) -> dict:
                global_injector.check("leader.reconcile_rpc")
                return json.loads(http_post(
                    w + "/worker/delete",
                    json.dumps({"names": sorted(ns)}).encode(),
                    timeout=120.0, headers=self._epoch_headers(),
                    origin=self.url))

            try:
                resp = self._worker_call_fenced(w, rpc)
            except Exception as e:
                global_metrics.inc("reconcile_failures")
                log.warning("delete push failed (sweep will retry)",
                            worker=w, err=repr(e))
                continue
            self.placement.moved_resolved(w, set(ns))
            deleted += int(resp.get("deleted", 0))
        if deleted:
            # the landed engine deletes shifted worker-side df: results
            # cached since the first bump were computed pre-delete
            self.bump_result_generation()
        global_metrics.inc("docs_cluster_deleted", len(names))
        return {"forgotten": len(names), "deleted": deleted}

    def _delete_flush_ok(self) -> bool:
        """Make a delete's placement removal durable. True when the
        flush landed OR persistence is structurally off (per-tenure
        map / no store bound — nothing to resurrect from); False only
        when a real durable map exists and could not be updated."""
        if (self.config.placement_flush_ms < 0
                or not self.placement._persist_enabled):
            return True
        try:
            return self.placement.flush()
        except Exception as e:
            log.warning("delete placement flush failed", err=repr(e))
            return False

    def leader_download_stream(self, rel: str):
        """Locate a document and return a readable stream + size for
        chunked proxying: local disk first, else probe every worker and
        stream the first hit through (``Leader.java:95-151`` serves
        ``FileSystemResource`` streams; buffering whole files per
        request would hold a thread's memory hostage at GB scale).

        Returns ``(fileobj, size | None)`` or ``None``; the caller owns
        closing the fileobj."""
        local = self.engine.open_document_stream(rel)
        if local is not None:
            return local
        try:   # the leader's durable recovery store is a local source too
            path = self._store_path(rel)
            if os.path.isfile(path):
                return open(path, "rb"), os.path.getsize(path)
        except PermissionError:
            raise
        except Exception:
            pass
        q = urllib.parse.quote(rel)
        for w in self.registry.get_all_service_addresses():
            if self.resilience.board.is_open(w):
                continue   # skip sick workers; another may hold the doc
            try:
                # breaker-tracked, no retry: probing the NEXT worker is
                # this loop's retry. A 404 (doc lives elsewhere) is an
                # app-level answer from a healthy worker — it does not
                # count against the breaker.
                resp = self.resilience.worker_call(
                    w, lambda w=w: http_get_stream(
                        w + f"/worker/download?path={q}", timeout=30.0,
                        origin=self.url),
                    retry=False)
                size = resp.headers.get("Content-Length")
                return resp, (int(size) if size is not None else None)
            except Exception:
                continue   # first 2xx wins; probe the next (Leader.java:144)
        return None

    def leader_download(self, rel: str) -> bytes | None:
        """Buffered convenience wrapper over the streaming path."""
        got = self.leader_download_stream(rel)
        if got is None:
            return None
        stream, _size = got
        try:
            return stream.read()
        finally:
            stream.close()

    def read_download_stream(self, rel: str):
        """The read plane's download locator (the shared
        ``/leader/download`` handler calls this on every host): a node
        serves from its engine + durable store, then probes workers."""
        return self.leader_download_stream(rel)

    # ---- mutation-plane role gate (cluster/router.py) ----

    def _should_forward_writes(self) -> bool:
        """Should this node forward a front-door mutation to the
        elected leader instead of serving it? True only for a
        NON-leader with a known, distinct leader — the mutation plane
        (placement routing, replication bookkeeping, cache
        invalidation) is leader-only state, and a worker accepting an
        upload would place documents its leader's map never learns
        about. When no leader is published (mid-election) the legacy
        local path still answers rather than failing closed."""
        if not self.config.router_forward_writes \
                or self._role == "leader":
            return False
        leader = self.leader_url()
        return bool(leader) and leader.rstrip("/") != self.url

    def read_plane_snapshot(self) -> dict:
        """``GET /api/router`` on a node: which placement world this
        node's read plane routes under (the CLI routers summary
        compares routers' views against the leader's)."""
        out = {"role": self._role, "url": self.url}
        if self._role == "leader":
            with self._placement_lock:
                docs = len(self._placement)
            out["placement"] = {"authoritative": True, "docs": docs,
                                "epoch": self.placement.epoch,
                                "gen": self.placement.gen}
        elif self._follower_active():
            out["placement"] = dict(
                self.placement_follower.view_snapshot(),
                authoritative=False)
        else:
            out["placement"] = {"authoritative": False, "loaded": False}
        return out


# the shared threaded HTTP server (cluster/router.py); the old name is
# kept for tests and embedding code
_NodeServer = _PlaneServer


class _NodeHandler(_HttpHandlerBase):
    """The symmetric node's HTTP surface: the shared read-plane routes
    (search / download / metrics / traces — cluster/router.py) plus
    the worker data plane, the leadership fence, and the leader-only
    ops endpoints."""

    node: SearchNode   # bound by SearchNode.__init__

    def _fence_check(self) -> bool:
        """Leadership fence on the mutating worker endpoints
        (``/worker/upload[-batch]``, ``/worker/delete``): a request
        stamped with a LOWER epoch than the highest this worker ever
        saw is answered with the distinct fence status (403 +
        ``X-Fence-Rejected: 1``) — the sender is a deposed leader and
        must step down, not retry. Unstamped requests (external /
        reference clients, single-node mode) are never fenced. Returns
        True when the rejection was sent. Callers read the body BEFORE
        checking so a rejected keep-alive connection stays in sync."""
        hdr = self.headers.get(FENCE_HEADER)
        if hdr is None:
            return False
        try:
            epoch = int(hdr)
        except ValueError:
            return False
        node = self.node
        global_injector.check("worker.fence")
        if node.fence.observe(epoch):
            return False
        current = node.fence.current()
        global_metrics.inc("fence_rejections")
        log.warning("fenced a stale-leader write", stale_epoch=epoch,
                    current_epoch=current, path=self.path)
        self._send(FENCE_STATUS, b"stale leader epoch",
                   "text/plain; charset=utf-8",
                   headers={FENCE_REJECTED_HEADER: "1",
                            FENCE_EPOCH_HEADER: str(current)})
        return True

    # ---- routing ----

    def do_GET(self) -> None:
        u = urllib.parse.urlparse(self.path)
        node = self.node
        self._last_span = None
        try:
            if not self._proto_gate(u.path):
                return
            if u.path == "/api/health":
                # the reserved observability lane: never admission-
                # controlled, never blocks on coordination or serving
                # locks (role is the cached last transition, depth is a
                # gauge read) — so operators can SEE a shedding node.
                # Each connection gets its own handler thread, so a
                # saturated bulk flood cannot queue ahead of this.
                self._json({
                    "ok": True, "role": node._role,
                    "proto_version": PROTO_VERSION,
                    "scatter_queue_depth": global_metrics.get(
                        "last_scatter_queue_depth", 0.0),
                    "admission": node.admission.snapshot(),
                    # embedding-column summary (dims, docs embedded,
                    # bytes resident) for the CLI status fan-out; null
                    # when the dense plane is disabled
                    "embedding": node.engine.dense_stats(),
                    # tiered-postings residency counters (ISSUE 18):
                    # hot/cold segment counts, HBM bytes vs budget,
                    # hit/skip rates — {"enabled": false} when off.
                    # JSON body only; no header/endpoint change, so
                    # the wire fingerprint is untouched.
                    "tier": node.engine.tier_stats(),
                    # compute-plane health (ISSUE 20): the per-worker
                    # device state machine (healthy|degraded|sick),
                    # fault/fallback counters, and whether a host
                    # mirror exists for this snapshot — the leader's
                    # placement and the router's owner-merge read this
                    # to route around a sick device. JSON body only.
                    "compute": node.engine.compute_stats()})
            elif u.path == "/api/ready":
                # readinessProbe target (deploy/k8s.yaml): a SICK
                # compute plane with no host fallback cannot answer
                # queries — take the pod out of Service endpoints
                # until the device recovers. Degraded (host-fallback)
                # serving stays READY: slower, but exact. Liveness
                # stays /api/health — a sick device is not a reason
                # to restart the process (restart would not heal HBM,
                # and the WAL replay would just add downtime).
                cs = node.engine.compute_stats()
                if cs.get("state") == "sick" and not cs.get(
                        "fallback_available"):
                    self._json({"ready": False, "compute": cs}, 503,
                               headers={"Retry-After": "1"})
                else:
                    self._json({"ready": True, "compute": cs})
            elif u.path == "/api/quarantine":
                # poison-query quarantine table (leader/router-side
                # state; a plain worker answers an empty table) — the
                # CLI `quarantine` command reads this
                self._json(node.quarantine.snapshot())
            elif u.path == "/api/device-nemesis":
                # armed compute-chaos rules (observability; the POST
                # that arms them is config-gated — see do_POST)
                from tfidf_tpu.utils.device_nemesis import \
                    global_device_nemesis as _dn
                if not node.config.device_nemesis_api:
                    self._text("device nemesis disabled "
                               "(config.device_nemesis_api=False)", 403)
                    return
                self._json(_dn.snapshot())
            elif u.path == "/worker/index-size":
                self._text(str(node.engine.index_size_bytes()))
            elif u.path == "/worker/names":
                # ground truth for the leader's residue anti-entropy
                # pass: what THIS engine actually serves (names: null
                # when the index layout cannot list — mesh layouts)
                self._json({"names": node.engine.document_names()})
            elif u.path == "/worker/download":
                self._download_from_engine(u)
            elif u.path == "/leader/download":
                # the front door guards every /leader/* endpoint:
                # checkpoint downloads are bulk transfers (real file
                # I/O per request), first to shed under backpressure —
                # the shared read-plane branch (cluster/router.py)
                self._serve_leader_download(u)
            elif u.path == "/api/status":
                # same phrasing as Controllers.java:25-29
                self._text("I am the leader" if node.is_leader()
                           else "I am a worker node")
            elif u.path == "/api/services":
                self._json(node.registry.get_all_service_addresses())
            elif u.path == "/api/leader":
                # the published /leader_info znode over HTTP: the
                # leader leaves the worker pool on promotion, so
                # /api/services alone cannot name it — clients (and
                # the CLI trace fan-out, whose request spans live in
                # the LEADER's ring) discover it here from any node
                try:
                    addr = read_leader_info(node.coord)
                except Exception:
                    addr = None
                self._json({"leader": addr})
            elif u.path == "/api/drain":
                # drain progress for one worker. Leader-only like the
                # POST: a follower's placement map is reset on demotion,
                # so it would answer a vacuous {"drained": true} and an
                # operator's --wait poll could decommission a worker
                # that still holds docs under the real leader
                if not node.is_leader():
                    self._text("not the leader", 409)
                    return
                worker = self._query_param(u, "worker")
                if not worker:
                    self._text("missing worker", 400)
                    return
                self._json(node.rebalancer.drain_status(worker))
            elif u.path == "/api/autopilot":
                # autopilot state + decision-audit ring (observability
                # lane, never admission-controlled — an operator must
                # be able to audit the controller exactly while the
                # cluster it steers is shedding). ?recent=N bounds the
                # decision records returned (default 50).
                try:
                    n = int(self._query_param(u, "recent") or 50)
                except ValueError:
                    n = 50
                self._json({"autopilot": node.autopilot.snapshot(),
                            "decisions": node.autopilot.decisions(n)})
            elif u.path == "/api/router":
                # which placement world this node's read plane routes
                # under (leader: the authoritative map; worker: its
                # follower view) — the CLI routers summary compares
                # router views against the leader's answer here
                self._json(node.read_plane_snapshot())
            elif u.path == "/api/routers":
                # the registered stateless-router tier (ephemeral
                # znodes under /router_registry — cluster/router.py)
                self._json(list_routers(node.coord))
            elif self._serve_metrics(u):
                # /metrics + /api/metrics: the shared exposition branch
                # (cluster/router.py; observability lane, never
                # admission-controlled)
                pass
            elif self._serve_trace(u):
                # trace export: the shared branch (cluster/router.py;
                # observability lane, never admission-controlled)
                pass
            else:
                self._text("not found", 404)
        except Exception as e:
            self._fail_500(u, e)

    def do_POST(self) -> None:
        u = urllib.parse.urlparse(self.path)
        node = self.node
        self._last_span = None
        try:
            if not self._proto_gate(u.path):
                return
            if u.path == "/worker/process":
                # same deadline refusal as the batched endpoint: the
                # leader's per-query path propagates X-Deadline-Ms too,
                # and scoring for a caller that already gave up burns
                # device time nobody merges. External reference clients
                # never send the header — parity behavior is untouched.
                if self._past_deadline():
                    return
                global_injector.check("worker.process")
                query = self._read_query()
                # the reply is emitted INSIDE the propagated span so a
                # leader-traced request's answer carries X-Trace-Id
                # (graftcheck protocol finding, fixed: replies sent
                # after the `with` closed were never trace-stamped —
                # the runtime protocol witness pins this)
                with self._worker_span("worker.process"):
                    try:
                        hits = node.worker_search(query)
                    except Exception as e:
                        # reference returns [] on any failure
                        # (Worker.java:183)
                        log.warning("search failed", err=repr(e))
                        hits = []
                    # queries_served is counted once, by Searcher.search
                    # (the degraded flag is popped even on this parity
                    # endpoint: a stale thread-local would mis-stamp
                    # the NEXT batch this handler thread serves)
                    dh = ({"X-Compute-Degraded": "1"}
                          if node.engine.pop_fallback_served() else None)
                    self._json([{"document": {"name": h.name},
                                 "score": h.score} for h in hits],
                               headers=dh)
            elif u.path == "/worker/process-batch":
                # batched scatter RPC (leader-internal; packed reply —
                # see cluster/wire.py). The per-query endpoint above
                # keeps the reference-compatible JSON shape. With
                # "names" the request is an ownership SLICE (failover /
                # hedged re-issue): score only those documents, exact
                # within the slice.
                global_injector.check("worker.process")
                # propagated scatter budget: the leader's remaining
                # milliseconds at dispatch; a batch whose budget is
                # already gone is refused with a 504 the resilience
                # layer treats as non-retryable — scoring it would
                # burn device time nobody will merge (the deadline is
                # re-checked after the NRT commit in
                # _search_batch_guarded)
                if self._past_deadline():
                    return
                deadline = self._deadline_header()
                req = json.loads(self._body().decode("utf-8"))
                queries = [str(q) for q in req.get("queries", ())]
                k = req.get("k")
                names = req.get("names")
                # hybrid plan (wire v3): "mode" selects which scoring
                # stages run. Absent -> sparse, so v2 leaders are
                # untouched; a v2 WORKER ignoring the field replies n
                # lists where the leader expects 2n and the leader's
                # slot-count check degrades honestly (never merges a
                # misaligned reply).
                mode = str(req.get("mode", "sparse"))
                # continues the leader's scatter trace (propagated
                # headers); the engine's trace_phase events and the
                # pipeline stage events land inside this span — and so
                # do the REPLIES (200, 500, and the 504 deadline
                # refusal): _send stamps X-Trace-Id from the active
                # span, so the reply the leader logs on a failed
                # scatter leg joins the trace (graftcheck protocol
                # finding, fixed — replies used to be emitted after
                # the span closed and were never stamped; the runtime
                # protocol witness pins this)
                with self._worker_span(
                        "worker.process_batch",
                        queries=len(queries),
                        slice=len(names) if names is not None
                        else 0):
                    try:
                        if names is not None and mode != "sparse":
                            body = pack_hit_lists(
                                node.worker_search_slice_staged(
                                    queries, [str(n) for n in names],
                                    mode, deadline=deadline))
                        elif names is not None:
                            body = pack_hit_lists(
                                node.worker_search_slice(
                                    queries, [str(n) for n in names],
                                    deadline=deadline))
                        elif mode != "sparse":
                            body = node.worker_search_staged_wire(
                                queries,
                                k=int(k) if k is not None else None,
                                mode=mode, deadline=deadline)
                        else:
                            body = node.worker_search_batch_wire(
                                queries,
                                k=int(k) if k is not None else None,
                                deadline=deadline)
                    except WorkerDeadline as e:
                        span_event("worker_deadline_refused")
                        self._send(504, f"{e}".encode(),
                                   "text/plain; charset=utf-8",
                                   headers={"X-Deadline-Exceeded": "1"})
                        return
                    except Exception as e:
                        # honest failure propagation (ADVICE r5): an
                        # engine failure must surface as a 5xx the
                        # leader counts in scatter_failures — NOT as an
                        # HTTP 200 all-empty reply it would merge as a
                        # valid zero-hit result. (The per-query
                        # /worker/process endpoint above keeps the
                        # reference's []-on-failure parity shape,
                        # Worker.java:183; this endpoint is
                        # leader-internal.) A classified compute fault
                        # rides X-Compute-Fault so the leader's retry
                        # gate and quarantine see the taxonomy instead
                        # of string-matching the repr; a poisoned
                        # output additionally names the guilty query
                        # rows (X-Poison-Fingerprints) so the
                        # quarantine never blames innocent cohort
                        # queries that merely shared the batch.
                        global_metrics.inc("worker_batch_failures")
                        span_event("worker_batch_failed",
                                   err=repr(e)[:120])
                        log.warning("batch search failed", err=repr(e))
                        eh: dict[str, str] = {}
                        fault = classify_compute_fault(e)
                        if fault is not None:
                            eh["X-Compute-Fault"] = fault
                            qrows = getattr(e, "queries", ())
                            if fault == "poison" and qrows:
                                eh["X-Poison-Fingerprints"] = ",".join(
                                    poison_fingerprint(q, mode)
                                    for q in qrows)
                        self._send(
                            500,
                            f"batch search failed: {e!r}".encode(),
                            "text/plain; charset=utf-8", headers=eh)
                        return
                    # host-fallback honesty: when the engine served
                    # this batch from the numpy mirror (degraded, not
                    # wrong — scores are bit-exact), say so on the
                    # wire so the leader can surface X-Compute-Degraded
                    # end-to-end instead of silently presenting sick
                    # hardware as healthy
                    dh = ({"X-Compute-Degraded": "1"}
                          if node.engine.pop_fallback_served() else None)
                    self._send(200, body, "application/octet-stream",
                               headers=dh)
            elif u.path == "/worker/upload":
                name, data = self._read_upload(u)
                if self._fence_check():   # after the body read: the
                    return                # rejected conn stays in sync
                if not name:
                    self._text("missing file name", 400)
                    return
                global_injector.check("worker.upload")
                # docs_indexed is counted once, by the index add path;
                # the commit is deferred to the next search (NRT policy,
                # see SearchNode.commit_if_dirty) — the raw file is
                # already durable on disk at this point
                try:
                    node.engine.ingest_bytes(name, data,
                                             save_to_disk=True)
                except UnsupportedMediaType as e:
                    # the Tika-parity contract: extract or refuse loudly,
                    # never index binary bytes as mojibake
                    self._text(f"unsupported media type: {e}", 415)
                    return
                except OSError as e:
                    if not storage.is_enospc(e):
                        raise
                    # disk full: the distinct 507 — non-retryable by
                    # classification and never a breaker trip (a node
                    # with a full disk still serves reads perfectly)
                    self._text("insufficient storage (disk full)", 507)
                    return
                node.notify_write()
                # a direct worker-side write also changes THIS node's
                # df — keep its own result cache honest (dual-role and
                # single-node deployments serve /leader/start here too)
                node.bump_result_generation()
                self._text(f"File {name} uploaded and indexed")
            elif u.path == "/worker/upload-batch":
                docs = json.loads(self._body().decode("utf-8"))
                if self._fence_check():
                    return
                global_injector.check("worker.upload")
                skipped = []
                staged: list[tuple] = []   # (name, tmp, path, text)
                enospc = False
                durable = node.config.storage_fsync
                try:
                    # two-phase group commit (fsync-before-ack without
                    # one fsync per document): stage every temp, ONE
                    # committer round over all of them, then publish
                    # renames + index, then ONE round over the unique
                    # directories — 2 fsync rounds per batch
                    for d in docs:
                        try:
                            staged.append((d["name"],
                                           *node.engine.stage_bytes(
                                               d["name"],
                                               d["text"].encode(
                                                   "utf-8"))))
                        except UnsupportedMediaType as e:
                            skipped.append({"name": d["name"],
                                            "error": str(e)})
                        except OSError as e:
                            if not storage.is_enospc(e):
                                raise
                            enospc = True
                            break
                    # ENOSPC is mapped to 507 from the fsync rounds
                    # too: with delayed allocation, fsync can be the
                    # FIRST syscall to report a full disk — a 500 here
                    # would trip the breaker the 507 contract protects
                    try:
                        if durable and staged and not enospc:
                            storage.global_committer.sync(
                                [t[1] for t in staged])
                        dirs: set = set()
                        if not enospc:
                            for name, tmp, path, text in staged:
                                node.engine.publish_staged(
                                    name, tmp, path, text)
                                dirs.add(os.path.dirname(path))
                            staged = []
                        if durable and dirs:
                            storage.global_committer.sync(sorted(dirs))
                    except OSError as e:
                        if not storage.is_enospc(e):
                            raise
                        enospc = True
                finally:
                    for _name, tmp, _path, _text in staged:
                        node.engine.discard_staged(tmp)
                    # mark dirty even on a mid-batch failure: the docs
                    # already ingested must become searchable at the
                    # next NRT flush, not be stranded uncommitted
                    if docs:
                        node.notify_write()
                        node.bump_result_generation()
                if enospc:
                    self._text("insufficient storage (disk full)", 507)
                    return
                self._json({"indexed": len(docs) - len(skipped),
                            "skipped": skipped})
            elif u.path == "/worker/delete":
                # shard-recovery reconciliation: remove moved documents
                # from index AND disk (a boot re-walk must not resurrect
                # them). Framework addition — the reference cannot move
                # documents between workers at all.
                names = json.loads(self._body().decode("utf-8"))
                if self._fence_check():
                    return
                names = names.get("names", []) if isinstance(names, dict) \
                    else names
                removed = sum(
                    bool(node.engine.remove_document(str(n)))
                    for n in names)
                if removed:
                    node.notify_write()
                    node.bump_result_generation()
                self._json({"deleted": removed})
            elif u.path == "/api/drain":
                # planned decommission: migrate the worker empty before
                # it leaves (leader-only — the drain mutates the
                # authoritative placement map). Body: {"worker": url,
                # "cancel": bool?}. The draining flag is durable, so a
                # leader failover restarts the drain.
                if not node.is_leader():
                    self._text("not the leader", 409)
                    return
                req = json.loads(self._body().decode("utf-8"))
                worker = req.get("worker")
                if not isinstance(worker, str) or not worker:
                    self._text("missing worker", 400)
                    return
                if req.get("cancel"):
                    self._json(node.rebalancer.cancel_drain(worker))
                else:
                    self._json(node.rebalancer.start_drain(worker))
            elif u.path == "/api/autopilot":
                # the runtime kill switch. Body: {"enabled": bool}.
                # Disabling reverts every managed knob to its static
                # config value BEFORE the reply is sent — the caller
                # observes a cluster already back on hand-tuned
                # constants. Acts on THIS node's autopilot (the loop
                # does work only while leader, so point it at the
                # leader); not admission-controlled — the switch must
                # work exactly when the front door sheds.
                req = json.loads(self._body().decode("utf-8"))
                if not isinstance(req, dict) or not isinstance(
                        req.get("enabled"), bool):
                    self._text("body must be {\"enabled\": bool}", 400)
                    return
                self._json({"autopilot":
                            node.autopilot.set_enabled(req["enabled"])})
            elif u.path == "/api/quarantine":
                # operator override after a fix rolls out: drop every
                # poison verdict on THIS node's read plane
                self._json({"cleared": node.quarantine.clear()})
            elif u.path == "/api/device-nemesis":
                # scriptable compute-plane chaos (ISSUE 20,
                # utils/device_nemesis.py) — double-gated: the config
                # knob must opt in AND the rule grammar is the same
                # one TFIDF_DEVICE_NEMESIS accepts. Body:
                # {"script": "site:kind[:prob[:k=v;...]] ..."} to arm,
                # {"clear": true} to drop rules + lift sick,
                # {"heal": true} to lift sick only. Never enabled in
                # production configs; refusing with 403 (not 404)
                # makes a misconfigured chaos suite loud.
                from tfidf_tpu.utils.device_nemesis import \
                    global_device_nemesis as _dn
                if not node.config.device_nemesis_api:
                    self._text("device nemesis disabled "
                               "(config.device_nemesis_api=False)", 403)
                    return
                req = json.loads(self._body().decode("utf-8"))
                if req.get("clear"):
                    _dn.clear()
                elif req.get("heal"):
                    _dn.heal()
                spec = req.get("script")
                rids = _dn.script(str(spec)) if spec else []
                self._json({"armed": _dn.armed, "sick": _dn.sick,
                            "rules": rids})
            elif u.path == "/admin/checkpoint":
                # on-demand durability point (reference analog: the
                # per-upload indexWriter.commit(), Worker.java:138)
                node.commit_if_dirty()
                try:
                    self._json(node.save_checkpoint())
                except OSError as e:
                    if not storage.is_enospc(e):
                        raise
                    self._text("insufficient storage (disk full)", 507)
            elif u.path == "/admin/scrub":
                # on-demand integrity-scrub pass (README "Storage
                # durability & integrity"); the sweep loop runs the
                # same pass on the storage_scrub_ms cadence
                self._json(node.run_integrity_scrub())
            elif u.path == "/leader/upload-batch":
                # uploads are bulk by default: first to shed under
                # backpressure, so ingest never crowds out interactive
                # search latency (admit BEFORE reading the body — a
                # shed upload pays at most the 1 MB drain in _shed,
                # never a JSON parse or an index slot). Mutations stay
                # on the elected leader: a non-leader forwards instead
                # of mutating state its leader's map never learns of.
                if node._should_forward_writes():
                    self._forward_write(u)
                    return
                with self._admitted("leader.upload_batch",
                                    LANE_BULK) as (sp, _lane):
                    if sp is None:
                        return
                    docs = json.loads(self._body().decode("utf-8"))
                    sp.set_attr("docs", len(docs)
                                if isinstance(docs, list) else 0)
                    try:
                        self._json(node.leader_upload_batch(docs))
                    except ValueError as e:  # malformed client payload
                        self._text(str(e), 400)
                    except urllib.error.HTTPError as e:
                        if e.code != storage.STORAGE_FULL_STATUS:
                            raise
                        # every replica leg reported a full disk:
                        # relay the distinct verdict
                        self._text("insufficient storage "
                                   "(worker disks full)", 507)
            elif u.path == "/leader/start":
                # the shared read-plane search branch
                # (cluster/router.py): front-door admission BEFORE any
                # work is queued, the trace span minted at the
                # admission point, the degraded header + (epoch,
                # generation) route stamp on the reply. Served by
                # EVERY node — a non-leader routes through its
                # placement follower view.
                self._serve_search()
            elif u.path == "/leader/delete":
                # placement-aware cluster-wide deletion (the upsert/
                # delete/search partition workload's delete leg); bulk
                # lane like every other mutating front-door endpoint.
                # Mutation plane: non-leaders forward to the leader.
                if node._should_forward_writes():
                    self._forward_write(u)
                    return
                with self._admitted("leader.delete",
                                    LANE_BULK) as (sp, _lane):
                    if sp is None:
                        return
                    req = json.loads(self._body().decode("utf-8"))
                    names = req.get("names", []) \
                        if isinstance(req, dict) else req
                    sp.set_attr("names", len(names))
                    self._json(node.leader_delete(
                        [str(n) for n in names]))
            elif u.path == "/leader/upload":
                if node._should_forward_writes():
                    self._forward_write(u)
                    return
                with self._admitted("leader.upload",
                                    LANE_BULK) as (sp, _lane):
                    if sp is None:
                        return
                    name, data = self._read_upload(u)
                    if not name:
                        self._text("missing file name", 400)
                        return
                    sp.set_attr("file", name)
                    try:
                        result = node.leader_upload(name, data)
                    except urllib.error.HTTPError as e:
                        if e.code == 415:  # worker refused the format
                            self._text("unsupported media type", 415)
                            return
                        if e.code == storage.STORAGE_FULL_STATUS:
                            # relay the worker's disk-full verdict
                            # distinctly: the client must not classify
                            # a full disk as a retryable 5xx
                            self._text("insufficient storage "
                                       "(worker disk full)", 507)
                            return
                        raise
                    self._text(f"File uploaded successfully to worker: "
                               f"{result['worker']}")
            else:
                self._text("not found", 404)
        except Exception as e:
            self._fail_500(u, e)

    def _download_from_engine(self, u) -> None:
        # URL-decode + traversal check live in Engine._safe_doc_path
        # (Worker.java:97-121 parity)
        rel = urllib.parse.unquote(self._query_param(u, "path") or "")
        try:
            got = self.node.engine.open_document_stream(rel)
        except PermissionError:
            self._text("invalid path", 400)
            return
        if got is None:
            self._text("not found", 404)
        else:
            self._stream(*got)

"""NemesisNet — scripted network faults at the cluster's HTTP seams.

Every chaos harness before this one kills *processes*; none breaks the
*network* — yet partitions, not crashes, are where distributed search
engines silently corrupt state (the reference's only protection is
ZooKeeper session expiry, PAPER.md §1). This module is the missing
nemesis: a transport shim consulted by the shared HTTP client seams —
``node.http_get`` / ``node.http_post`` / ``_ScatterClient.post`` (the
leader→worker data plane), ``coordination.CoordinationClient._rpc`` /
``_poll`` (the control plane), and ``ensemble._post_json`` (Raft peer
replication) — so tests script per-link faults without monkeypatching a
single call site:

- **drop** — the request never leaves the source (symmetric partitions
  compose from two one-way drops; raised as
  :class:`NemesisPartitioned`, a ``ConnectionRefusedError``, so every
  existing failure classifier treats it exactly like a dead link);
- **drop_reply** — the request IS delivered and processed, the reply is
  lost (:class:`NemesisReplyLost`, a ``ConnectionResetError``): the
  jepsen-critical ambiguous-delivery case — an acked-on-the-wire write
  whose ack never arrives;
- **delay** — injected latency (+ optional jitter) before the request
  goes out: the gray-failure generator for the latency-EWMA breaker;
- **truncate** / **corrupt** — the reply arrives damaged, exercising
  the wire layer's ValueError contract and the scatter failure paths;
- **skew** — outbound request headers are masked per link
  (:meth:`NemesisNet.filter_headers`, consulted by the same client
  seams), simulating an old-binary peer that never learned them: the
  version-skew generator for the rolling-upgrade chaos schedule
  (``make chaos-upgrade``; see cluster/protover.py).

Links are identified by ``(source endpoint, destination endpoint)``
where an endpoint is ``host:port``. Sources are stamped on the client
objects (``SearchNode.start`` sets its scatter client's and
coordination client's ``origin``; ensemble members pass
``my_address``); traffic with an unknown source matches only
wildcard-source rules. Self-links (``src == dst``) are exempt — a real
partition never cuts a node's loopback to itself.

The shim is a process-global singleton (:data:`global_nemesis`, like
``faults.global_injector``) so multi-node in-process tests script one
fault plan for the whole cluster. With no rules armed the fast path is
one tuple-emptiness check per RPC; readers never take the lock (the
rule list is replaced copy-on-write).
"""

from __future__ import annotations

import random
import threading
import time
import urllib.parse
from dataclasses import dataclass

from tfidf_tpu.utils.logging import get_logger
from tfidf_tpu.utils.metrics import global_metrics

log = get_logger("cluster.nemesis")

DROP = "drop"
DROP_REPLY = "drop_reply"
DELAY = "delay"
TRUNCATE = "truncate"
CORRUPT = "corrupt"
SKEW = "skew"


class NemesisFault(ConnectionError):
    """Base class for injected network faults (tests catch this)."""


class NemesisPartitioned(NemesisFault, ConnectionRefusedError):
    """The request never left the source: the link is partitioned.
    A ``ConnectionRefusedError`` on purpose — provably undelivered, so
    the coordination client's mutation-retry rule and the resilience
    classifiers treat it exactly like a refused TCP connect."""


class NemesisReplyLost(NemesisFault, ConnectionResetError):
    """The request WAS delivered and processed; the reply was lost.
    A ``ConnectionResetError`` on purpose — ambiguous delivery, so a
    coordination mutation must NOT blindly re-send (the write may have
    committed) while idempotent reads may retry."""


def endpoint_of(url_or_addr: str | None) -> str:
    """Normalize a URL or ``host:port`` string to the ``host:port``
    endpoint identity the rule tables key on ('' for unknown)."""
    if not url_or_addr:
        return ""
    s = url_or_addr.strip()
    if "//" in s:
        u = urllib.parse.urlparse(s)
        host = u.hostname or ""
        return f"{host}:{u.port}" if u.port else host
    return s.rstrip("/")


def _ep_set(eps) -> frozenset:
    if eps is None:
        return None
    if isinstance(eps, str):
        eps = (eps,)
    return frozenset(endpoint_of(e) for e in eps)


@dataclass(frozen=True)
class _Rule:
    rid: int
    kind: str
    src: frozenset | None       # None = any KNOWN-or-unknown source
    dst: frozenset | None       # None = any destination
    probability: float = 1.0
    delay_s: float = 0.0
    jitter_s: float = 0.0
    keep_bytes: int = 0         # truncate: reply bytes kept
    # skew: lowercased header names masked off src→dst requests (an
    # old-binary peer that never sends them)
    strip: frozenset | None = None
    # both endpoints inside this set -> the rule does not apply (an
    # isolated MINORITY keeps its internal links; see isolate())
    exempt: frozenset | None = None

    def matches(self, src: str, dst: str) -> bool:
        if src and src == dst:
            return False        # loopback-to-self is never partitioned
        if self.exempt is not None and src in self.exempt \
                and dst in self.exempt:
            return False
        if self.src is not None and src not in self.src:
            return False
        if self.dst is not None and dst not in self.dst:
            return False
        return True


class NemesisNet:
    """The scripted fault plan. All mutators replace the rule tuple
    copy-on-write under a writer lock; the per-RPC read path is
    lock-free (one attribute read + emptiness check)."""

    def __init__(self, seed: int = 0, sleep=time.sleep) -> None:
        self._lock = threading.Lock()       # writers only
        self._rules: tuple[_Rule, ...] = ()
        self._next_id = 1
        # shared across reader threads without a lock: probability and
        # jitter draws need randomness, not thread-safety guarantees
        self._rng = random.Random(seed)
        # injectable like RetryPolicy's: the delay only ever fires when
        # a chaos test ARMED a latency rule — production traffic (no
        # rules) never sleeps here, so the lockgraph pass deliberately
        # does not model armed-nemesis latency as a blocking callee
        # (same discipline as the paced-sleep allowlist precedent)
        self._sleep = sleep

    # ---- scripting API ----

    def _add(self, kind: str, src, dst, **kw) -> int:
        with self._lock:
            rid = self._next_id
            self._next_id += 1
            rule = _Rule(rid, kind, _ep_set(src), _ep_set(dst), **kw)
            self._rules = self._rules + (rule,)
        log.info("nemesis rule armed", kind=kind, rule=rid)
        return rid

    def drop(self, src=None, dst=None, probability: float = 1.0) -> int:
        """One-way request drop: traffic src→dst never leaves src."""
        return self._add(DROP, src, dst, probability=probability)

    def drop_reply(self, src=None, dst=None,
                   probability: float = 1.0) -> int:
        """Deliver src→dst requests but lose the replies (ambiguous
        delivery — the jepsen acked-write-loss probe)."""
        return self._add(DROP_REPLY, src, dst, probability=probability)

    def delay(self, src=None, dst=None, delay_s: float = 0.05,
              jitter_s: float = 0.0, probability: float = 1.0) -> int:
        """Inject latency (+ uniform jitter) before src→dst requests."""
        return self._add(DELAY, src, dst, delay_s=delay_s,
                         jitter_s=jitter_s, probability=probability)

    def truncate(self, src=None, dst=None, keep_bytes: int = 8,
                 probability: float = 1.0) -> int:
        """Cut src→dst replies down to ``keep_bytes`` bytes."""
        return self._add(TRUNCATE, src, dst, keep_bytes=keep_bytes,
                         probability=probability)

    def corrupt(self, src=None, dst=None,
                probability: float = 1.0) -> int:
        """Flip bytes in src→dst replies (wire-validation exercise)."""
        return self._add(CORRUPT, src, dst, probability=probability)

    def skew(self, src=None, dst=None,
             strip=("X-Proto-Version",), probability: float = 1.0) -> int:
        """Version-skew: mask ``strip`` headers off src→dst requests so
        the destination sees an old-binary peer (a request with no
        ``X-Proto-Version`` is implicitly wire version 1 — see
        cluster/protover.py). The rolling-upgrade chaos schedule arms
        this per link to hold mixed-version traffic on the cluster
        while processes restart one at a time."""
        return self._add(SKEW, src, dst, probability=probability,
                         strip=frozenset(h.lower() for h in strip))

    def one_way(self, a, b) -> int:
        """Asymmetric partition: a→b requests drop; b→a flows."""
        return self.drop(src=a, dst=b)

    def partition(self, a, b) -> list[int]:
        """Symmetric partition between endpoint sets ``a`` and ``b``."""
        return [self.drop(src=a, dst=b), self.drop(src=b, dst=a)]

    def isolate(self, endpoints) -> list[int]:
        """Cut ``endpoints`` off from everyone else (both directions).
        Links WITHIN the set — including self-links — keep working: an
        isolated minority still talks among itself, like a real
        partition."""
        eps = _ep_set(endpoints)
        rules = []
        with self._lock:
            for src, dst in ((eps, None), (None, eps)):
                rid = self._next_id
                self._next_id += 1
                self._rules = self._rules + (
                    _Rule(rid, DROP, src, dst, exempt=eps),)
                rules.append(rid)
        log.info("nemesis isolation armed", endpoints=sorted(eps))
        return rules

    def remove(self, rid: int) -> None:
        with self._lock:
            self._rules = tuple(r for r in self._rules if r.rid != rid)

    def heal(self) -> None:
        """Clear every rule (the partition heals)."""
        with self._lock:
            n = len(self._rules)
            self._rules = ()
        if n:
            log.info("nemesis healed", rules_cleared=n)

    def active(self) -> bool:
        return bool(self._rules)

    # ---- the seams ----

    def check_send(self, src, dst) -> None:
        """Called by a transport seam BEFORE a request goes out. May
        raise :class:`NemesisPartitioned` (dropped link) or sleep
        (injected latency)."""
        rules = self._rules
        if not rules:
            return
        s, d = endpoint_of(src), endpoint_of(dst)
        delay = 0.0
        for r in rules:
            if r.kind not in (DROP, DELAY) or not r.matches(s, d):
                continue
            if r.probability < 1.0 and self._rng.random() > r.probability:
                continue
            if r.kind == DROP:
                global_metrics.inc("nemesis_drops")
                raise NemesisPartitioned(
                    f"nemesis: link {s or '?'} -> {d} is partitioned")
            delay += r.delay_s + (self._rng.random() * r.jitter_s
                                  if r.jitter_s > 0 else 0.0)
        if delay > 0:
            global_metrics.inc("nemesis_delays")
            self._sleep(delay)

    def filter_headers(self, src, dst, headers: dict) -> dict:
        """Called by a transport seam with the outbound request headers
        BEFORE they go out; returns the (possibly masked) headers the
        destination will actually see. Only skew rules apply — with
        none armed this returns ``headers`` untouched (same emptiness
        fast path as the other seams)."""
        rules = self._rules
        if not rules:
            return headers
        s, d = endpoint_of(src), endpoint_of(dst)
        strip: set[str] = set()
        for r in rules:
            if r.kind != SKEW or not r.matches(s, d):
                continue
            if r.probability < 1.0 and self._rng.random() > r.probability:
                continue
            strip |= r.strip or frozenset()
        if not strip:
            return headers
        masked = {k: v for k, v in headers.items()
                  if k.lower() not in strip}
        if len(masked) != len(headers):
            global_metrics.inc("nemesis_header_masks")
        return masked

    def filter_reply(self, src, dst, body: bytes) -> bytes:
        """Called by a transport seam AFTER the reply bytes arrived.
        May raise :class:`NemesisReplyLost` (the request was processed;
        its reply is gone) or return damaged bytes."""
        rules = self._rules
        if not rules:
            return body
        s, d = endpoint_of(src), endpoint_of(dst)
        for r in rules:
            if r.kind not in (DROP_REPLY, TRUNCATE, CORRUPT) \
                    or not r.matches(s, d):
                continue
            if r.probability < 1.0 and self._rng.random() > r.probability:
                continue
            if r.kind == DROP_REPLY:
                global_metrics.inc("nemesis_reply_drops")
                raise NemesisReplyLost(
                    f"nemesis: reply {d} -> {s or '?'} lost "
                    f"(request was delivered)")
            if r.kind == TRUNCATE:
                global_metrics.inc("nemesis_corruptions")
                body = body[:max(0, r.keep_bytes)]
            elif r.kind == CORRUPT:
                global_metrics.inc("nemesis_corruptions")
                head = bytes(b ^ 0x5A for b in body[:64])
                body = head + body[64:]
        return body


# Process-wide nemesis used by the library seams; tests script it.
global_nemesis = NemesisNet()

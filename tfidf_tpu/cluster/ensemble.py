"""Replicated coordination ensemble — Raft-style, over the HTTP plumbing.

The reference points its clients at a ZooKeeper *ensemble* and gets
leader-based quorum replication for free (``ZookeeperConfig.java:15-21``).
This module closes that gap for the framework's own substrate: an
:class:`EnsembleNode` wraps one :class:`~.coordination.CoordinationCore`
per coordinator process and replicates its command log across peers with
the understandable-consensus recipe of Raft (Ongaro & Ousterhout,
ATC'14), persisted through :class:`~.wal.DurableStore`:

- **Terms + persisted votes** — ``current_term`` / ``voted_for`` are
  fsynced (``meta.json``) before any vote or append response leaves the
  node, so a restart can never double-vote in a term.
- **Leader append / quorum commit** — every client write becomes a WAL
  entry on the leader, is replicated via ``POST /ensemble/append``, and
  is **acknowledged only after a majority has it durably** (then applied
  to the deterministic core). A 3-member ensemble therefore survives
  SIGKILL of any single member — leader included — with zero lost
  acknowledged writes.
- **Follower write-redirect** — client-facing ops on a follower answer
  421 + the leader hint (``coordination._CoordHandler._gate_leader``);
  the client's multi-address failover follows it.
- **Leader-owned session-expiry clock** — only the leader's reaper may
  declare a session dead, and the expiry itself is a *logged command*
  (``expire_session``) so every replica drops the same ephemerals at the
  same log position. A freshly-elected leader grants all sessions a
  liveness grace (``core.touch_all_sessions``) before its clock starts.
- **Snapshots + log compaction** — every ``snapshot_every`` applied
  commands the core state is snapshotted and the WAL truncated; a
  far-behind or fresh peer is caught up via ``POST /ensemble/snapshot``.

A standalone durable coordinator is simply an ensemble of one: quorum
size 1 means append+fsync *is* commit, and restart recovery replays
snapshot + WAL into the core.

Fault points: ``ensemble.vote`` (handling a RequestVote),
``ensemble.replicate_append.<peer>`` (leader about to send
AppendEntries/InstallSnapshot to that peer).
"""

from __future__ import annotations

import json
import random
import threading
import time
import urllib.error
import urllib.request

from tfidf_tpu.cluster.coordination import (CoordinationCore,
                                            CoordinationUnavailable,
                                            NotLeaderError)
from tfidf_tpu.cluster.nemesis import global_nemesis
from tfidf_tpu.cluster.protover import proto_headers
from tfidf_tpu.cluster.wal import DurableStore
from tfidf_tpu.utils.faults import global_injector
from tfidf_tpu.utils.logging import get_logger
from tfidf_tpu.utils.metrics import global_metrics

log = get_logger("cluster.ensemble")

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"
_ROLE_GAUGE = {FOLLOWER: 0, CANDIDATE: 1, LEADER: 2}
_MAX_BATCH = 128          # entries per AppendEntries RPC


def _post_json(address: str, path: str, obj: dict,
               timeout_s: float, origin: str = "") -> dict:
    # peer-replication seam for the network nemesis (cluster/nemesis.py):
    # ensemble splits are scripted per (member, member) link
    global_nemesis.check_send(origin, address)
    body = json.dumps(obj).encode()
    h = {"Content-Type": "application/json"}
    h.update(proto_headers())
    h = global_nemesis.filter_headers(origin, address, h)
    req = urllib.request.Request(
        f"http://{address}{path}", data=body, headers=h)
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        return json.loads(global_nemesis.filter_reply(
            origin, address, resp.read()))


class _Waiter:
    __slots__ = ("term", "event", "result", "error")

    def __init__(self, term: int) -> None:
        self.term = term
        self.event = threading.Event()
        self.result: object = None
        self.error: Exception | None = None


class EnsembleNode:
    """One member of the replicated coordination ensemble.

    Owns the durable store (WAL + snapshots + hard state), the in-memory
    log suffix, and the Raft role machinery; mutates ``core`` only by
    applying committed log entries in order.
    """

    def __init__(self, core: CoordinationCore, data_dir: str, node_id: str,
                 peers: dict[str, str], my_address: str,
                 election_timeout_s: float = 1.0,
                 heartbeat_interval_s: float = 0.25,
                 commit_timeout_s: float = 5.0,
                 snapshot_every: int = 512,
                 wal_fsync: bool = True,
                 rpc_timeout_s: float = 2.0) -> None:
        self.core = core
        self.node_id = node_id
        self.peers = dict(peers)            # id -> "host:port" (not self)
        self.my_address = my_address
        self.election_timeout_s = election_timeout_s
        self.heartbeat_interval_s = heartbeat_interval_s
        self.commit_timeout_s = commit_timeout_s
        self.snapshot_every = max(1, snapshot_every)
        self.rpc_timeout_s = rpc_timeout_s

        self._lock = threading.RLock()
        self._alive = threading.Event()
        self._alive.set()
        self._rng = random.Random(f"{node_id}:{my_address}")

        # --- durable recovery: snapshot -> core, WAL -> in-memory log ---
        self.store = DurableStore(data_dir, fsync=wal_fsync)
        meta, snapshot, entries = self.store.load()
        self.term: int = int(meta.get("term", 0))
        self.voted_for: str | None = meta.get("voted_for")
        if snapshot is not None:
            self.base_index = int(snapshot["last_index"])
            self.base_term = int(snapshot["last_term"])
            self._snap_state = snapshot["state"]
            self.core.restore_state(self._snap_state)
        else:
            self.base_index = 0
            self.base_term = 0
            self._snap_state = self.core.state_snapshot()
        self.entries: list[dict] = entries      # {"i","t","c"}, i > base
        # Raft: commit_index is NOT persisted — recovered entries are
        # re-applied only once commitment is re-established (instantly
        # for a solo node; via the new leader's appends otherwise)
        self.commit_index = self.base_index
        self.last_applied = self.base_index
        self._applied_since_snap = 0
        self._snap_in_progress = False

        self.role = FOLLOWER
        # a new leader may not SERVE until its term-start no-op commits
        # (Raft §8): before that, its state machine may lag the log it
        # holds (e.g. a restarted solo node pre-replay) — readiness is
        # commit_index reaching the no-op's index
        self._ready_index = 0
        self.leader_id: str | None = None
        self._last_heartbeat = time.monotonic()
        self._timeout = self._new_timeout()
        self._next_index: dict[str, int] = {}
        self._match_index: dict[str, int] = {}
        self._waiters: dict[int, _Waiter] = {}
        self._rep_events: dict[str, threading.Event] = {
            pid: threading.Event() for pid in self.peers}
        self._threads: list[threading.Thread] = []

        # route all core mutations through quorum replication; only the
        # leader's reaper may run the session-expiry clock
        self.core._submit = self.submit
        self.core.expiry_enabled = self.is_leader
        self._publish_gauges()
        log.info("ensemble member recovered", node=node_id,
                 term=self.term, base=self.base_index,
                 wal_entries=len(self.entries), peers=sorted(self.peers))

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def start(self) -> None:
        if not self.peers:
            # ensemble of one: quorum = 1, leadership is unconditional
            with self._lock:
                self._become_leader_locked()
        t = threading.Thread(target=self._election_loop, daemon=True,
                             name=f"ensemble-elect-{self.node_id}")
        t.start()
        self._threads.append(t)
        for pid in self.peers:
            t = threading.Thread(target=self._replicate_loop, args=(pid,),
                                 daemon=True,
                                 name=f"ensemble-rep-{self.node_id}-{pid}")
            t.start()
            self._threads.append(t)

    def close(self) -> None:
        self._alive.clear()
        for ev in self._rep_events.values():
            ev.set()
        with self._lock:
            self._fail_waiters_locked(
                CoordinationUnavailable("ensemble member shutting down"))
        self.store.close()

    def kill(self) -> None:
        """Crash simulation — identical to :meth:`close` on purpose:
        neither path flushes anything the append path hasn't already
        fsynced, so recovery exercises the real WAL contract."""
        self.close()

    # ------------------------------------------------------------------
    # log helpers (call with self._lock held)
    # ------------------------------------------------------------------

    def last_index(self) -> int:
        return self.base_index + len(self.entries)

    def _term_at(self, index: int) -> int:
        if index == self.base_index:
            return self.base_term
        if index < self.base_index or index > self.last_index():
            raise IndexError(index)
        return self.entries[index - self.base_index - 1]["t"]

    def _last_log_term(self) -> int:
        return self.entries[-1]["t"] if self.entries else self.base_term

    def _majority(self) -> int:
        return (len(self.peers) + 1) // 2 + 1

    def is_leader(self) -> bool:
        """Leader AND ready to serve: the term-start no-op has committed,
        so every entry from prior terms is applied to the core."""
        return (self.role == LEADER and self._alive.is_set()
                and self.commit_index >= self._ready_index)

    def leader_address(self) -> str | None:
        with self._lock:
            if self.role == LEADER:
                return self.my_address
            if self.leader_id is not None:
                return self.peers.get(self.leader_id)
            return None

    def status(self) -> dict:
        with self._lock:
            return {"node_id": self.node_id, "role": self.role,
                    "term": self.term, "leader": self.leader_id,
                    "last_index": self.last_index(),
                    "commit_index": self.commit_index,
                    "applied": self.last_applied,
                    "base_index": self.base_index,
                    "peers": sorted(self.peers)}

    def _publish_gauges(self) -> None:
        g = global_metrics.set_gauge
        g(f"ensemble_role_{self.node_id}", _ROLE_GAUGE[self.role])
        g(f"ensemble_term_{self.node_id}", self.term)
        g(f"ensemble_commit_{self.node_id}", self.commit_index)
        if self.role == LEADER and self.peers:
            lag = self.last_index() - min(
                self._match_index.get(p, 0) for p in self.peers)
            g(f"ensemble_replication_lag_{self.node_id}", lag)

    # ------------------------------------------------------------------
    # client writes: leader append -> quorum commit -> apply -> ack
    # ------------------------------------------------------------------

    def submit(self, cmd: dict) -> object:
        with self._lock:
            if not self._alive.is_set():
                raise CoordinationUnavailable("ensemble member stopped")
            if self.role != LEADER:
                raise NotLeaderError(self.leader_address())
            index = self.last_index() + 1
            entry = {"i": index, "t": self.term, "c": cmd}
            # durability FIRST: a failed append must never be acked
            self.store.append([entry])
            self.entries.append(entry)
            waiter = _Waiter(self.term)
            self._waiters[index] = waiter
            if not self.peers:
                self._advance_commit_locked()
        self._kick_replicators()
        if not waiter.event.wait(self.commit_timeout_s):
            with self._lock:
                self._waiters.pop(index, None)
            global_metrics.inc("ensemble_commit_timeouts")
            raise CoordinationUnavailable(
                f"no quorum within {self.commit_timeout_s}s "
                f"(write NOT acknowledged)")
        if waiter.error is not None:
            raise waiter.error
        self._maybe_snapshot()
        return waiter.result

    def _kick_replicators(self) -> None:
        for ev in self._rep_events.values():
            ev.set()

    def _advance_commit_locked(self) -> None:
        """Leader: commit = highest n with a durable majority AND
        n's entry from the current term (Raft §5.4.2)."""
        for n in range(self.last_index(), self.commit_index, -1):
            if self._term_at(n) != self.term:
                break
            votes = 1 + sum(1 for p in self.peers
                            if self._match_index.get(p, 0) >= n)
            if votes >= self._majority():
                self.commit_index = n
                break
        self._apply_committed_locked()

    def _apply_committed_locked(self) -> None:
        while self.last_applied < self.commit_index:
            e = self.entries[self.last_applied - self.base_index]
            self.last_applied += 1
            try:
                result, error = self.core.apply(e["c"]), None
            except Exception as ex:   # deterministic app error (result)
                result, error = None, ex
            w = self._waiters.pop(e["i"], None)
            if w is not None:
                if w.term != e["t"]:
                    w.error = NotLeaderError(self.leader_address())
                else:
                    w.result, w.error = result, error
                w.event.set()
            self._applied_since_snap += 1
        self._publish_gauges()

    def _maybe_snapshot(self) -> None:
        """Snapshot + compact when due. Called from OUTSIDE the
        ensemble lock: the expensive half (full-state JSON + fsync)
        runs unlocked so heartbeats, votes, and appends are never
        stalled behind a large snapshot write (which would trigger
        spurious elections)."""
        with self._lock:
            if (self._applied_since_snap < self.snapshot_every
                    or self._snap_in_progress
                    or not self._alive.is_set()):
                return
            self._snap_in_progress = True
            snap_index = self.last_applied
            snap_term = self._term_at(snap_index)
            state = self.core.state_snapshot()
        try:
            self.store.write_snapshot(state, snap_index, snap_term)
            with self._lock:
                remaining = [e for e in self.entries
                             if e["i"] > snap_index]
                self.store.rewrite(remaining)
                self._snap_state = state
                self.base_index = snap_index
                self.base_term = snap_term
                self.entries = remaining
                self._applied_since_snap = self.last_applied - snap_index
            log.info("snapshot saved", node=self.node_id,
                     last_index=snap_index, wal_entries=len(remaining))
        except Exception as e:
            log.warning("snapshot failed", node=self.node_id,
                        err=repr(e))
        finally:
            self._snap_in_progress = False

    def _fail_waiters_locked(self, exc: Exception) -> None:
        for w in self._waiters.values():
            w.error = exc
            w.event.set()
        self._waiters.clear()

    # ------------------------------------------------------------------
    # terms / roles
    # ------------------------------------------------------------------

    def _persist_meta_locked(self) -> None:
        self.store.set_meta(self.term, self.voted_for)

    def _observe_term_locked(self, term: int) -> None:
        if term > self.term:
            self.term = term
            self.voted_for = None
            self._persist_meta_locked()
            if self.role != FOLLOWER:
                log.info("stepping down", node=self.node_id, term=term)
            self._step_down_locked()

    def _step_down_locked(self) -> None:
        self.role = FOLLOWER
        # AMBIGUOUS, not NotLeaderError: the waiter's entry is already
        # durably in our log and may still commit under the new leader —
        # a retry-safe 421 would let the client re-send the mutation and
        # commit it twice (e.g. two EPHEMERAL_SEQUENTIAL znodes)
        self._fail_waiters_locked(CoordinationUnavailable(
            "leadership lost mid-commit; write outcome unknown"))
        self._publish_gauges()

    def _become_leader_locked(self) -> None:
        self.role = LEADER
        self.leader_id = self.node_id
        # not ready to serve until the no-op below commits
        self._ready_index = self.last_index() + 1
        for pid in self.peers:
            self._next_index[pid] = self.last_index() + 1
            self._match_index[pid] = 0
        # commit a no-op from the new term so prior-term entries commit
        # (Raft §8) and the tenure is findable in the log
        entry = {"i": self.last_index() + 1, "t": self.term,
                 "c": {"op": "noop"}}
        self.store.append([entry])
        self.entries.append(entry)
        if not self.peers:
            self._advance_commit_locked()
        # sessions get a fresh grace before the new expiry clock starts
        self.core.touch_all_sessions()
        global_metrics.inc("ensemble_elections_won")
        self._publish_gauges()
        log.info("became ensemble leader", node=self.node_id,
                 term=self.term, last_index=self.last_index())
        self._kick_replicators()

    # ------------------------------------------------------------------
    # election
    # ------------------------------------------------------------------

    def _new_timeout(self) -> float:
        return self.election_timeout_s * (1.0 + self._rng.random())

    def _election_loop(self) -> None:
        while self._alive.is_set():
            time.sleep(self.election_timeout_s / 8)
            self._maybe_snapshot()     # catch-all (e.g. boot recovery)
            with self._lock:
                if (self.role == LEADER
                        or time.monotonic() - self._last_heartbeat
                        < self._timeout):
                    continue
                if not self.peers:
                    self._become_leader_locked()
                    continue
                # start an election
                self.term += 1
                self.voted_for = self.node_id
                self._persist_meta_locked()
                self.role = CANDIDATE
                self.leader_id = None
                self._last_heartbeat = time.monotonic()
                self._timeout = self._new_timeout()
                term = self.term
                req = {"term": term, "candidate": self.node_id,
                       "last_log_index": self.last_index(),
                       "last_log_term": self._last_log_term()}
                peers = dict(self.peers)
                self._publish_gauges()
            global_metrics.inc("ensemble_elections_started")
            log.info("election started", node=self.node_id, term=term)
            votes = {"n": 1}
            for pid, addr in peers.items():
                threading.Thread(
                    target=self._request_vote, daemon=True,
                    args=(pid, addr, req, votes),
                    name=f"ensemble-vote-{self.node_id}-{pid}").start()

    def _request_vote(self, pid: str, addr: str, req: dict,
                      votes: dict) -> None:
        try:
            resp = _post_json(addr, "/ensemble/vote", req,
                              self.rpc_timeout_s,
                              origin=self.my_address)
        except Exception:
            return
        with self._lock:
            if resp.get("term", 0) > self.term:
                self._observe_term_locked(resp["term"])
                return
            if (self.role != CANDIDATE or self.term != req["term"]
                    or not resp.get("granted")):
                return
            votes["n"] += 1
            if votes["n"] >= self._majority():
                self._become_leader_locked()

    def handle_vote(self, req: dict) -> dict:
        global_injector.check("ensemble.vote")
        with self._lock:
            if req["term"] < self.term:
                return {"term": self.term, "granted": False}
            self._observe_term_locked(req["term"])
            up_to_date = ((req["last_log_term"], req["last_log_index"])
                          >= (self._last_log_term(), self.last_index()))
            if (self.voted_for in (None, req["candidate"])
                    and up_to_date):
                if self.voted_for != req["candidate"]:
                    self.voted_for = req["candidate"]
                    self._persist_meta_locked()
                self._last_heartbeat = time.monotonic()
                return {"term": self.term, "granted": True}
            return {"term": self.term, "granted": False}

    # ------------------------------------------------------------------
    # replication (leader side)
    # ------------------------------------------------------------------

    def _replicate_loop(self, pid: str) -> None:
        ev = self._rep_events[pid]
        while self._alive.is_set():
            ev.wait(self.heartbeat_interval_s)
            ev.clear()
            if not self._alive.is_set() or self.role != LEADER:
                continue
            try:
                self._sync_peer(pid)
            except Exception as e:
                global_metrics.inc("ensemble_replicate_failures")
                log.debug("replication to peer failed", peer=pid,
                          err=repr(e))
            self._maybe_snapshot()

    def _sync_peer(self, pid: str) -> None:
        """One catch-up pass: send appends (or a snapshot) until the
        peer matches our last index or we stop being leader."""
        addr = self.peers[pid]
        for _ in range(64):       # bounded catch-up per pass
            with self._lock:
                if self.role != LEADER or not self._alive.is_set():
                    return
                ni = self._next_index.get(pid, self.last_index() + 1)
                if ni <= self.base_index:
                    req = {"kind": "snapshot", "term": self.term,
                           "leader_id": self.node_id,
                           "last_index": self.base_index,
                           "last_term": self.base_term,
                           "state": self._snap_state}
                else:
                    prev = ni - 1
                    lo = prev - self.base_index
                    ents = self.entries[lo:lo + _MAX_BATCH]
                    req = {"kind": "append", "term": self.term,
                           "leader_id": self.node_id,
                           "prev_index": prev,
                           "prev_term": self._term_at(prev),
                           "entries": ents,
                           "commit": self.commit_index}
                term_sent = self.term
            global_injector.check(f"ensemble.replicate_append.{pid}")
            if req["kind"] == "snapshot":
                resp = _post_json(addr, "/ensemble/snapshot", req,
                                  self.rpc_timeout_s,
                                  origin=self.my_address)
                with self._lock:
                    if resp.get("term", 0) > self.term:
                        self._observe_term_locked(resp["term"])
                        return
                    self._next_index[pid] = req["last_index"] + 1
                    self._match_index[pid] = max(
                        self._match_index.get(pid, 0), req["last_index"])
                continue
            resp = _post_json(addr, "/ensemble/append", req,
                              self.rpc_timeout_s,
                              origin=self.my_address)
            with self._lock:
                if resp.get("term", 0) > self.term:
                    self._observe_term_locked(resp["term"])
                    return
                if self.role != LEADER or self.term != term_sent:
                    return
                if resp.get("success"):
                    match = req["prev_index"] + len(req["entries"])
                    self._match_index[pid] = max(
                        self._match_index.get(pid, 0), match)
                    self._next_index[pid] = self._match_index[pid] + 1
                    self._advance_commit_locked()
                    if self._match_index[pid] >= self.last_index():
                        return
                else:
                    hint = resp.get("hint")
                    nxt = self._next_index.get(pid, 1) - 1
                    if hint is not None:
                        nxt = min(nxt, int(hint) + 1)
                    self._next_index[pid] = max(1, nxt)

    # ------------------------------------------------------------------
    # replication (follower side)
    # ------------------------------------------------------------------

    def handle_append(self, req: dict) -> dict:
        resp = self._handle_append_locked(req)
        if resp.get("success"):
            self._maybe_snapshot()     # compaction outside the lock
        return resp

    def _handle_append_locked(self, req: dict) -> dict:
        with self._lock:
            if req["term"] < self.term:
                return {"term": self.term, "success": False}
            self._observe_term_locked(req["term"])
            if self.role != FOLLOWER:
                self._step_down_locked()
            self.leader_id = req["leader_id"]
            self._last_heartbeat = time.monotonic()
            prev_i, prev_t = req["prev_index"], req["prev_term"]
            if prev_i > self.last_index():
                return {"term": self.term, "success": False,
                        "hint": self.last_index()}
            if prev_i >= self.base_index and \
                    self._term_at(prev_i) != prev_t:
                # conflicting suffix: drop it (durably) and ask for more
                keep = [e for e in self.entries if e["i"] < prev_i]
                self.store.rewrite(keep)
                self.entries = keep
                return {"term": self.term, "success": False,
                        "hint": max(self.base_index, prev_i - 1)}
            new: list[dict] = []
            for e in req["entries"]:
                if e["i"] <= self.base_index:
                    continue
                if e["i"] <= self.last_index():
                    if self._term_at(e["i"]) == e["t"]:
                        continue
                    keep = [x for x in self.entries if x["i"] < e["i"]]
                    self.store.rewrite(keep)
                    self.entries = keep
                new.append(e)
            if new:
                self.store.append(new)
                self.entries.extend(new)
            self.commit_index = max(
                self.commit_index,
                min(int(req.get("commit", 0)), self.last_index()))
            self._apply_committed_locked()
            return {"term": self.term, "success": True}

    def handle_install_snapshot(self, req: dict) -> dict:
        with self._lock:
            if req["term"] < self.term:
                return {"term": self.term}
            self._observe_term_locked(req["term"])
            self.leader_id = req["leader_id"]
            self._last_heartbeat = time.monotonic()
            li, lt = int(req["last_index"]), int(req["last_term"])
            if li <= self.base_index:
                return {"term": self.term}
            self.store.save_snapshot(req["state"], li, lt, [])
            self._snap_state = req["state"]
            self.core.restore_state(req["state"])
            self.base_index = li
            self.base_term = lt
            self.entries = []
            self.commit_index = li
            self.last_applied = li
            self._applied_since_snap = 0
            self._publish_gauges()
            log.info("snapshot installed", node=self.node_id,
                     last_index=li, term=self.term)
            return {"term": self.term}

"""Overload-survival front door: admission control, priority lanes,
load shedding, and generation-keyed result caching.

The reference has nothing here — its Spring endpoints accept every
request and queue unboundedly (``Leader.java:39-92``), so a 2x traffic
spike collapses latency for everyone. This module gives the leader an
explicit admission layer, threaded through the ``/leader/*`` handlers
in :mod:`tfidf_tpu.cluster.node`:

- :class:`TokenBucket` / :class:`AdmissionController` — per-client
  token-bucket admission (client id from the ``X-Client-Id`` header or
  the peer IP) with an explicit shed path: a rejected request gets
  ``429`` + ``Retry-After`` instead of a queue slot, so the client
  learns to back off while admitted requests keep their latency.
- priority lanes — ``interactive`` (default) vs ``bulk`` (selected by
  the ``X-Priority: bulk`` header; uploads default to bulk). Under
  backpressure bulk sheds FIRST; the scatter coalescer's weighted
  dequeue (:mod:`tfidf_tpu.cluster.batcher`) guarantees bulk can never
  starve interactive inside an admitted batch either.
- backpressure — keyed on the scatter queue depth: the max of the
  ``last_scatter_queue_depth`` gauge the coalescer already publishes
  (the same signal the k8s HPA scales on) and the coalescer's live
  ``backlog()`` (the gauge is only refreshed at batch formation, so it
  freezes while every dispatcher is blocked in a stalled RPC — the
  live read keeps shedding honest through the stall). Above
  ``admission_queue_high_water`` the bulk lane sheds, above
  ``admission_queue_critical`` interactive sheds too. ``/api/health``
  and ``/api/metrics`` never pass through admission at all (the
  reserved observability lane), so operators can see a shedding
  cluster.
- :class:`ResultCache` — a leader-side query-result cache keyed by the
  node's df-signature + commit-generation token
  (:meth:`SearchNode.df_signature`): every mutation the leader
  orchestrates (confirmed upload legs, reconcile deletes, migration
  flips, membership transitions) advances the token, so a stale entry
  can never be served — correctness falls out of the same version
  plumbing that keys the engine's segment view cache, no TTLs
  involved. Degraded (possibly-incomplete) responses are never cached.
  The invalidation boundary is the cluster's WRITE CONTRACT: mutations
  flow through the leader's ``/leader/*`` front door. A direct
  ``/worker/*`` write on a multi-node topology bypasses the leader's
  placement/replication bookkeeping (the doc lands unmapped and
  unreplicated) and its cache invalidation alike — the worker-side
  ``bump_result_generation`` covers the single-node and dual-role
  deployments where that worker IS the leader.

Metrics: ``admission_admitted``, ``admission_shed_total``,
``admission_shed_rate_limited``, ``admission_shed_backpressure``,
per-lane ``admission_shed_{lane}``, gauges ``admission_last_depth`` /
``admission_clients``; ``cache_hits``, ``cache_misses``,
``cache_evictions``, ``cache_invalidations``, gauge ``cache_entries``.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass

from tfidf_tpu.utils.faults import global_injector
from tfidf_tpu.utils.logging import get_logger
from tfidf_tpu.utils.metrics import global_metrics
from tfidf_tpu.utils.tracing import span_event

log = get_logger("cluster.admission")

# the two request lanes. Interactive is the default for searches; bulk
# is selected by the ``X-Priority: bulk`` header and is the default for
# uploads. Health/metrics endpoints have no lane: they are served
# outside admission entirely (the reserved observability path).
LANE_INTERACTIVE = "interactive"
LANE_BULK = "bulk"


@dataclass(frozen=True)
class AdmissionDecision:
    """One request's verdict. ``retry_after_s`` is the client's backoff
    hint (the 429 reply's ``Retry-After`` header); ``reason`` is the
    shed cause (``rate_limited`` | ``backpressure``) or ``""`` when
    admitted."""
    admitted: bool
    retry_after_s: float = 0.0
    reason: str = ""


_ADMIT = AdmissionDecision(True)


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/second refill up to
    ``burst`` capacity; each admitted request spends one token.

    ``try_take()`` returns 0.0 on admit, else the seconds until one
    token will be available (the ``Retry-After`` hint — honest, not a
    constant: a client that waits exactly that long is admitted)."""

    __slots__ = ("rate", "burst", "_tokens", "_t", "_lock")

    def __init__(self, rate: float, burst: float,
                 clock=time.monotonic) -> None:
        self.rate = rate
        self.burst = max(burst, 1.0)
        self._tokens = self.burst
        self._t = clock()
        self._lock = threading.Lock()

    def try_take(self, now: float) -> float:
        with self._lock:
            self._tokens = min(self.burst,
                               self._tokens + (now - self._t) * self.rate)
            self._t = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return 0.0
            return (1.0 - self._tokens) / self.rate


class AdmissionController:
    """The leader's front door. ``admit(client, lane)`` decides one
    request's fate from (a) the scatter queue-depth backpressure signal
    and (b) the client's token bucket.

    Shedding order under backpressure: bulk first (at the high-water
    mark), then interactive (at the critical mark) — never the
    health/metrics endpoints, which are not admission-controlled at
    all. Per-client buckets are bounded by ``admission_max_clients``
    (LRU eviction: memory safety for a million distinct client ids; an
    evicted flooder merely restarts with a full burst, which the depth
    backpressure still bounds)."""

    def __init__(self, config, depth_fn, clock=time.monotonic,
                 name: str = "") -> None:
        """``name`` identifies WHICH front door this controller guards
        (the stateless router tier passes ``router``) — surfaced in
        :meth:`snapshot` for /api/health. Metric names stay identical
        across tiers on purpose: each router is its own process, so
        Prometheus separates tiers by scrape target, not by series
        name (the per-router queue-depth gauge the HPA consumes is
        already distinct via the coalescer name)."""
        self.name = name
        self.enabled = config.admission_enabled
        self.rate_qps = config.admission_rate_qps
        self.burst = (config.admission_burst
                      or 2.0 * config.admission_rate_qps)
        self.high_water = config.admission_queue_high_water
        self.critical = config.admission_queue_critical
        self.retry_after_s = config.admission_retry_after_s
        self.max_clients = max(1, config.admission_max_clients)
        self._depth_fn = depth_fn
        self._clock = clock
        self._lock = threading.Lock()
        self._buckets: OrderedDict[str, TokenBucket] = OrderedDict()

    def _bucket(self, client: str) -> TokenBucket:
        with self._lock:
            b = self._buckets.get(client)
            if b is None:
                b = self._buckets[client] = TokenBucket(
                    self.rate_qps, self.burst, clock=self._clock)
                while len(self._buckets) > self.max_clients:
                    self._buckets.popitem(last=False)
                global_metrics.set_gauge("admission_clients",
                                         len(self._buckets))
            else:
                self._buckets.move_to_end(client)
            return b

    def _shed(self, lane: str, reason: str,
              retry_after_s: float) -> AdmissionDecision:
        global_metrics.inc("admission_shed_total")
        global_metrics.inc(f"admission_shed_{reason}")
        global_metrics.inc(f"admission_shed_{lane}")
        # the request span is already active (minted at the handler's
        # admission point), so a shed is visible in its trace
        span_event("shed", reason=reason, lane=lane,
                   retry_after_s=round(retry_after_s, 3))
        return AdmissionDecision(False, retry_after_s, reason)

    def admit(self, client: str,
              lane: str = LANE_INTERACTIVE) -> AdmissionDecision:
        if not self.enabled:
            return _ADMIT
        global_injector.check("leader.admission")
        depth = float(self._depth_fn() or 0.0)
        global_metrics.set_gauge("admission_last_depth", depth)
        # backpressure first: a saturated pipeline sheds regardless of
        # any single client's budget — bulk at the high-water mark,
        # interactive only past critical
        if self.critical > 0 and depth >= self.critical:
            return self._shed(lane, "backpressure", self.retry_after_s)
        if (self.high_water > 0 and depth >= self.high_water
                and lane == LANE_BULK):
            return self._shed(lane, "backpressure", self.retry_after_s)
        if self.rate_qps > 0:
            wait = self._bucket(client).try_take(self._clock())
            if wait > 0.0:
                return self._shed(lane, "rate_limited", wait)
        global_metrics.inc("admission_admitted")
        return _ADMIT

    def snapshot(self) -> dict:
        """Operator view for /api/health (lock-light: counts only)."""
        with self._lock:
            n = len(self._buckets)
        return {"enabled": self.enabled, "front_door": self.name,
                "rate_qps": self.rate_qps,
                "burst": self.burst, "queue_high_water": self.high_water,
                "queue_critical": self.critical, "clients_tracked": n}


class ResultCache:
    """Generation-keyed LRU query-result cache.

    Every entry is stamped with the df-signature token current when its
    scatter was DISPATCHED; ``get`` returns it only while the node's
    token is unchanged. Any commit that could change a score — upsert,
    delete, migration flip, membership transition — advances the token,
    so staleness is impossible by construction (the invalidation rides
    the same version plumbing that keys the engine's segment view
    cache; there is no TTL to tune and no explicit invalidation call to
    forget). A stale entry found under a newer token is evicted on
    touch and counted as ``cache_invalidations``."""

    def __init__(self, max_entries: int) -> None:
        self.max_entries = max(1, max_entries)
        self._lock = threading.Lock()
        self._entries: OrderedDict[object, tuple[object, object]] = \
            OrderedDict()

    def get(self, key, token):
        """The cached value for ``key`` at generation ``token``, or
        None (counted as a miss; a generation mismatch also counts as
        an invalidation and evicts the dead entry)."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                global_metrics.inc("cache_misses")
                return None
            if e[0] != token:
                del self._entries[key]
                global_metrics.inc("cache_invalidations")
                global_metrics.inc("cache_misses")
                global_metrics.set_gauge("cache_entries",
                                         len(self._entries))
                return None
            self._entries.move_to_end(key)
            global_metrics.inc("cache_hits")
            return e[1]

    def put(self, key, token, value) -> None:
        with self._lock:
            self._entries[key] = (token, value)
            self._entries.move_to_end(key)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
                global_metrics.inc("cache_evictions")
            global_metrics.set_gauge("cache_entries", len(self._entries))

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

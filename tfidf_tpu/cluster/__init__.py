"""Cluster control plane.

The TPU-native replacement for the reference's coordination stack
(SURVEY.md §1, layers L0-L3):

- :mod:`coordination` — the L0 substrate: a small coordination service with
  ZooKeeper's znode semantics (persistent / ephemeral / ephemeral-sequential
  nodes, data payloads, one-shot watches, session-timeout liveness),
  embeddable in-process or served over HTTP to many node processes.
  Replaces the external ZooKeeper server (``config/ZookeeperConfig.java``).
- :mod:`election` — L1 leader election with the reference's exact
  predecessor-watch algorithm (``leader/LeaderElection.java``).
- :mod:`registry` — L1 service discovery (``registry/ServiceRegistry.java``).
- :mod:`node` — L2+L3: the symmetric node binary. Every node serves the
  worker data-plane API; the elected leader additionally serves the
  coordinator API (``leader/Leader.java``, ``worker/Worker.java``,
  ``controller/Controllers.java``).
- :mod:`resilience` — the failure discipline shared by every
  leader->worker RPC path: bounded retry with backoff + jitter,
  per-worker circuit breakers (closed/open/half-open), and the
  hedged-read laggard detector.
- :mod:`placement` — R-way document placement: the replica map with
  per-leg upload bookkeeping, per-query ownership assignment (exactly
  one live replica scores each document), and durable persistence of
  the map through the coordination substrate so leader failover keeps
  exact ownership.
- :mod:`router` — the scale-out query plane: the scatter read plane
  (owner-merge / failover / hedge spine) extracted from the node so it
  runs against a follower view of the placement znode, and the
  stateless :class:`~tfidf_tpu.cluster.router.QueryRouter` tier built
  on it (any-node reads; all mutations stay on the elected leader).
- :mod:`wal` — L0 durability: CRC-framed write-ahead log, atomic
  snapshots of the znode tree + session table, and log compaction, so a
  crashed coordinator restarts with its full state.
- :mod:`ensemble` — L0 replication: Raft-style terms/votes/quorum-commit
  over the WAL, turning the substrate into a 3-replica ensemble that
  survives the loss of any single member with zero lost acknowledged
  writes (the role ZooKeeper's ensemble plays for the reference).
"""

from tfidf_tpu.cluster.coordination import (CoordinationCore,
                                            CoordinationServer,
                                            CoordinationClient,
                                            LocalCoordination, Event)
from tfidf_tpu.cluster.election import LeaderElection, OnElectionCallback
from tfidf_tpu.cluster.registry import ServiceRegistry
from tfidf_tpu.cluster.resilience import (BreakerBoard, CircuitBreaker,
                                          CircuitOpenError, RetryPolicy)
from tfidf_tpu.cluster.node import SearchNode
from tfidf_tpu.cluster.placement import PlacementFollower, PlacementMap
from tfidf_tpu.cluster.router import QueryRouter
from tfidf_tpu.cluster.wal import DurableStore
from tfidf_tpu.cluster.ensemble import EnsembleNode

__all__ = [
    "CoordinationCore", "CoordinationServer", "CoordinationClient",
    "LocalCoordination", "Event", "LeaderElection", "OnElectionCallback",
    "ServiceRegistry", "SearchNode", "PlacementMap", "PlacementFollower",
    "QueryRouter", "RetryPolicy", "CircuitBreaker",
    "CircuitOpenError", "BreakerBoard", "DurableStore", "EnsembleNode",
]

"""Scale-out query plane: the scatter read plane + the stateless router.

The single-coordinator design inherited from the reference (every query
funnels through the elected leader's scatter loop, ``Leader.java:39-92``)
caps the whole cluster's interactive front door near one Python
process's worth of HTTP + merge work — ~92 q/s in OVERLOAD.json against
a 6,243 q/s engine. This module retires that ceiling by splitting the
node into two planes:

- **Read plane** (:class:`ScatterReadPlane`) — the scatter / owner-merge
  / failover / hedge spine, extracted from ``node.py`` so it no longer
  requires leadership. It runs against *any* placement view object: the
  leader's authoritative :class:`~tfidf_tpu.cluster.placement.PlacementMap`,
  or a read-only :class:`~tfidf_tpu.cluster.placement.PlacementFollower`
  loaded from the durable placement znode and refreshed by a data watch.
  A follower-routed merge NEVER falls back to the legacy sum-merge for
  names outside its view (with R replicas both copies would be summed —
  a silent double count); unknown names are dropped and the response is
  marked degraded instead. Every ``/leader/start`` reply is stamped with
  the ``(epoch, generation)`` pair it routed under, and a view that
  cannot be confirmed fresh (coordinator partition) degrades honestly
  (``X-Scatter-Degraded`` carrying ``stale_view=1``, result cache
  bypassed) and self-heals on the next watch fire.

- **Mutation plane** — stays on the elected leader: placement routing,
  replication, reconcile/repair, rebalancing, deletes. A router (and a
  non-leader node) forwards ``/leader/upload[-batch]`` / ``/leader/delete``
  to the leader published at ``/leader_info`` instead of serving them.

:class:`QueryRouter` is the dedicated stateless tier built on the read
plane (``python -m tfidf_tpu router``): it owns its OWN admission
controller, scatter coalescer, generation-keyed result cache, resilience
stack (breakers/retries/hedges/deadlines), and placement follower — so
admitted interactive throughput scales with router count (BENCH_r07)
while correctness still rests on per-request owner assignment. Routers
register ephemeral znodes under ``/router_registry`` so ``status`` and
``/api/routers`` can enumerate the tier; the k8s Deployment + HPA in
``deploy/k8s.yaml`` scale it on the per-router
``tfidf_last_router_scatter_queue_depth`` gauge.
"""

from __future__ import annotations

import contextlib
import email.parser
import email.policy
import json
import math
import threading
import time
import urllib.error
import urllib.parse
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutureTimeout
from concurrent.futures import wait as _fwait
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from tfidf_tpu.cluster.admission import (LANE_BULK, LANE_INTERACTIVE,
                                         AdmissionController, ResultCache)
from tfidf_tpu.cluster.autopilot import Autopilot
from tfidf_tpu.cluster.batcher import Coalescer
from tfidf_tpu.cluster.coordination import (EPHEMERAL_SEQUENTIAL,
                                            NoNodeError)
from tfidf_tpu.cluster.fusion import FUSION_METHODS, fuse
from tfidf_tpu.cluster.placement import PlacementFollower, PlacementMap
from tfidf_tpu.cluster.protover import (PROTO_HEADER,
                                        PROTO_REJECTED_HEADER,
                                        PROTO_STATUS, PROTO_VERSION,
                                        in_window, parse_version)
from tfidf_tpu.cluster.quarantine import (PoisonQuarantine,
                                          poison_fingerprint)
from tfidf_tpu.cluster.registry import ServiceRegistry, read_leader_info
from tfidf_tpu.cluster.resilience import (CircuitOpenError,
                                          ClusterResilience,
                                          DeadlineExpired, hedge_laggards)
from tfidf_tpu.cluster.wire import unpack_hit_lists
from tfidf_tpu.utils import storage as _storage
from tfidf_tpu.utils.config import Config
from tfidf_tpu.utils.faults import global_injector
from tfidf_tpu.utils.logging import get_logger
from tfidf_tpu.utils.metrics import global_metrics
from tfidf_tpu.utils.tracing import (SPAN_HEADER, TRACE_HEADER,
                                     global_tracer, remote_context,
                                     to_chrome_trace)

log = get_logger("cluster.router")

ROUTER_REGISTRY_NAMESPACE = "/router_registry"
ROUTER_PREFIX = "r_"


def register_router(coord, address: str) -> str:
    """Announce a router to the cluster: an ephemeral-sequential znode
    under ``/router_registry`` whose payload is the router's base URL
    (the same shape as the worker registry). ``/api/routers`` and the
    CLI ``status`` routers block enumerate these."""
    coord.ensure(ROUTER_REGISTRY_NAMESPACE)
    return coord.create(f"{ROUTER_REGISTRY_NAMESPACE}/{ROUTER_PREFIX}",
                        address.encode(), mode=EPHEMERAL_SEQUENTIAL)


def list_routers(coord) -> list[str]:
    """The registered router URLs (empty when none / namespace absent)."""
    try:
        names = coord.get_children(ROUTER_REGISTRY_NAMESPACE)
    except NoNodeError:
        return []
    out = []
    for name in names:
        try:
            out.append(coord.get_data(
                f"{ROUTER_REGISTRY_NAMESPACE}/{name}").decode())
        except NoNodeError:
            continue   # vanished between listing and read
    return out


class ScatterReadPlane:
    """The scatter/merge/failover/hedge spine, shared by the leader,
    any-node reads, and the stateless router tier.

    Hosts must provide (see ``SearchNode.__init__`` /
    ``QueryRouter.__init__``): ``config``, ``registry``, ``placement``,
    ``resilience``, ``_pool``, ``_slice_pool``, ``_scatter``,
    ``scatter_batcher``, ``result_cache``, ``hedge_ms``,
    ``_cluster_epoch``, ``_legacy_hit_workers``, ``_scatter_health``,
    and ``df_signature()``. The policy hooks below route reads through
    the right placement view:

    - :meth:`_read_placement` — the view THIS request routes under
      (authoritative map on the leader; follower view elsewhere).
      ``_gather_merge`` captures it ONCE per request and derives the
      merge policy from the captured object: a FOLLOWER view never
      legacy-sums names outside it (the view being behind means R
      replicas' copies would be silently double-counted — dropped and
      degraded instead), and the stale-view verdict comes from the
      same captured view (a role flip mid-request can change what
      ``_read_placement`` returns, never what this request routed
      under);
    - :meth:`_view_suspect` — whether the CURRENT view can be vouched
      for: gates the result-cache consult before dispatch.
    """

    # attribute contracts for the static analyzers (graftcheck): the
    # hosts construct these in their __init__
    config: Config
    registry: ServiceRegistry
    placement: PlacementMap
    resilience: ClusterResilience
    quarantine: PoisonQuarantine

    # ---- policy hooks ----

    def _read_placement(self) -> PlacementMap:
        """The placement view for one read request (default: the
        host's authoritative map)."""
        return self.placement

    def _view_suspect(self) -> bool:
        """Is the read view possibly stale (degrade honestly)? Gates
        the result-cache consult; the merge itself re-derives the
        marker from the ONE view it captured (see _gather_merge — the
        per-request honesty verdict must never consult ambient state
        a concurrent role flip can change mid-request)."""
        sus = getattr(self._read_placement(), "suspect", None)
        return bool(sus()) if sus is not None else False

    @staticmethod
    def _view_stamp(pmap) -> tuple[int | None, int]:
        """The ``(epoch, generation)`` pair a request routed under —
        stamped on every read reply (``X-Route-Epoch`` /
        ``X-Route-Generation``) so a client (and the chaos suites) can
        tell exactly which placement world produced a result."""
        if isinstance(pmap, PlacementFollower):
            return pmap.loaded_epoch, pmap.loaded_gen
        return pmap.epoch, pmap.gen

    # ---- read path (leader/Leader.java:39-92 lineage) ----

    def leader_search(self, query: str,
                      lane: str = LANE_INTERACTIVE) -> dict[str, float]:
        """Scatter-gather search (``Leader.java:39-92``): fan the query out
        to every registered worker, tolerate per-worker failure, merge
        scores by document name under the per-request owner assignment.

        Default path: concurrent queries coalesce into one batched RPC
        per worker (:meth:`_scatter_search_batch`). The per-query JSON
        fan-out below remains for unbounded-results (parity) configs and
        ``scatter_micro_batch=False``."""
        return self.leader_search_with_health(query, lane=lane)[0]

    # per-query JSON scatter budget (the reference's 10s RestTemplate
    # default) — propagated to workers as X-Deadline-Ms like the
    # batched path's scatter_timeout_s
    _PER_QUERY_BUDGET_S = 10.0

    def leader_search_with_health(self, query: str,
                                  lane: str = LANE_INTERACTIVE,
                                  mode: str = "sparse",
                                  fusion: str | None = None
                                  ) -> tuple[dict[str, float], dict]:
        """``leader_search`` plus this request's OWN health marker —
        ``(merged, {attempted, responded, circuit_open, degraded,
        failovers, dark, dropped, stale_view, ...})``. The handler
        stamps the degraded header from the returned value: reading it
        back off shared node state would let two concurrent scatters
        mislabel each other's replies.

        ``lane`` routes the query through the scatter coalescer's
        weighted dequeue (bulk can never starve interactive). The
        result cache is consulted first — but never while the read
        view is suspect (a stale router serving pre-partition cache
        entries would be silently wrong in exactly the window the
        degraded marker exists for). The generation token is captured
        BEFORE dispatch, so a commit (or view refresh) that lands
        mid-scatter invalidates the entry this request inserts."""
        token = self.df_signature()
        # hybrid plan (wire v3): mode/fusion compose into the cache key
        # (a hybrid result must never answer a sparse query or vice
        # versa) and ride the coalescer item so batches stay homogeneous
        # per (mode, fusion) via the group key.
        qkey = (query if mode == "sparse"
                else f"\x00{mode}\x00{fusion or ''}\x00{query}")
        cache = self.result_cache if not self._view_suspect() else None
        if cache is not None:
            hit = cache.get(qkey, token)
            if hit is not None:
                # a cache hit did no fan-out: its health marker says so
                # (and is never recorded into the shared gauges — it
                # would misreport the last real scatter's health). The
                # route stamp still applies: EVERY read reply names the
                # placement world it was served under, cached or not
                # (the entry's token is that world by construction).
                epoch, gen = self._view_stamp(self._read_placement())
                return hit, {"attempted": 0, "responded": 0,
                             "circuit_open": 0, "degraded": 0,
                             "failovers": 0, "dark": 0, "dropped": 0,
                             "stale_view": 0, "cached": 1,
                             "route_epoch": epoch, "route_gen": gen}
        if self.scatter_batcher is not None:
            result, health = self.scatter_batcher.submit(
                (query, mode, fusion), lane=1 if lane == LANE_BULK else 0)
            if cache is not None and not health.get("degraded"):
                cache.put(qkey, token, result)
            return result, health
        if mode != "sparse":
            # no coalescer (unbounded-results / micro-batch-off
            # configs): staged queries still go through the batched
            # scatter — the per-query JSON path below is sparse-only —
            # as a one-item batch
            result, health = self._scatter_search_batch(
                [(query, mode, fusion)])[0]
            if cache is not None and not health.get("degraded"):
                cache.put(qkey, token, result)
            return result, health
        log.info("scatter search", query=query)
        body = json.dumps({"query": query}).encode()
        t_deadline = time.monotonic() + self._PER_QUERY_BUDGET_S

        def rpc_one(addr: str, live: set[str],
                    deadline: float) -> list[list[tuple[str, float]]]:
            global_injector.check("leader.worker_rpc")
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                # pre-dispatch: no RPC happens, so the breaker must
                # record NOTHING (DeadlineExpired releases it)
                raise DeadlineExpired(addr + ": budget spent")
            hits = json.loads(self._scatter.post(
                addr, "/worker/process", body, timeout=remaining,
                live=live,
                headers={"X-Deadline-Ms": str(int(remaining * 1e3))}))
            return [[(h["document"]["name"], float(h["score"]))
                     for h in hits]]

        merged, health = self._gather_merge([query], rpc_one, t_deadline)
        result = self._order_merged(merged[0])
        if cache is not None and not health.get("degraded"):
            cache.put(query, token, result)
        return result, health

    def _record_scatter_health(self, attempted: int, responded: int,
                               circuit_open: int, failovers: int = 0,
                               dark: int = 0,
                               uncovered_workers: int = 0,
                               dropped: int = 0,
                               stale_view: int = 0) -> dict:
        """Publish one fan-out's health: gauges in /api/metrics plus a
        last-observed copy on the node (for the CLI summary). Returns
        the marker dict — the handler stamps the degraded header from
        the RETURNED value, which belongs to this request alone.

        ``degraded`` means the RESULTS may be incomplete or stale —
        not merely that a worker failed. A worker death fully absorbed
        by replica failover yields a complete, non-degraded response;
        documents with no live scorer (``dark``), a failed worker
        outside the view's knowledge, hits DROPPED because a follower
        view cannot merge them safely, and a view that cannot be
        confirmed fresh (``stale_view``) all keep the marker honest."""
        degraded = 1 if (dark > 0 or uncovered_workers > 0
                         or dropped > 0 or stale_view) else 0
        health = {
            "attempted": attempted, "responded": responded,
            "circuit_open": circuit_open, "degraded": degraded,
            "failovers": failovers, "dark": dark,
            "dropped": dropped, "stale_view": stale_view}
        self._scatter_health = health
        global_metrics.set_gauge("scatter_last_attempted", attempted)
        global_metrics.set_gauge("scatter_last_responded", responded)
        global_metrics.set_gauge("scatter_last_circuit_open", circuit_open)
        global_metrics.set_gauge("scatter_last_failovers", failovers)
        global_metrics.set_gauge("scatter_last_dark", dark)
        global_metrics.set_gauge("scatter_degraded", degraded)
        global_metrics.set_gauge("breaker_open_workers",
                                 self.resilience.board.open_count())
        if failovers:
            global_metrics.inc("scatter_failovers", failovers)
        if stale_view:
            global_metrics.inc("router_stale_responses")
        if degraded:
            global_metrics.inc("degraded_responses")
        return health

    def _order_merged(self, merged: dict[str, float]) -> dict[str, float]:
        """Truncate + order one query's sum-merged scores."""
        if not self.config.unbounded_results:
            # each document lives on exactly one worker, so the global
            # top-k is contained in the union of per-worker top-ks —
            # truncating the merge to k is exact
            merged = dict(sorted(merged.items(),
                                 key=lambda kv: (-kv[1], kv[0]))
                          [:self.config.top_k])
        if self.config.result_order == "name":
            # alphabetical, the reference's TreeMap order (Leader.java:80-91)
            return dict(sorted(merged.items()))
        return dict(sorted(merged.items(), key=lambda kv: (-kv[1], kv[0])))

    def _scatter_search_batch(
            self, queries: list[str]) -> list[dict[str, float]]:
        """Batched scatter-gather: ONE ``/worker/process-batch`` RPC per
        worker for a whole coalesced query group, packed-binary replies
        (:mod:`tfidf_tpu.cluster.wire`), per-query owner-merge at the
        gatherer (:meth:`_gather_merge`). Collapses the per-(query,
        worker) HTTP + JSON cost that otherwise caps the distributed
        path (the reference pays it by design, one RestTemplate POST
        per worker per query, ``Leader.java:51-70``). A failed worker's
        ownership slice fails over to surviving replicas WITHIN this
        request.

        Items are plain query strings (sparse) or ``(query, mode,
        fusion)`` tuples — the coalescer's group key keeps a batch
        homogeneous in (mode, fusion), so one batch runs ONE plan.
        Staged plans (mode dense|hybrid, wire v3) ask each worker for
        ``2n`` hit lists (n sparse + n dense), owner-merge each stage
        independently (per-stage global top-k is exact — one owner per
        doc), and fuse the two merged maps per query
        (:mod:`tfidf_tpu.cluster.fusion`)."""
        items = [(q, "sparse", None) if isinstance(q, str) else q
                 for q in queries]
        queries = [q for q, _m, _f in items]
        mode = items[0][1]
        fusion = items[0][2] or self.config.fusion_method
        staged = mode != "sparse"
        payload = {"queries": queries, "k": self.config.top_k}
        if staged:
            payload["mode"] = mode
        body = json.dumps(payload).encode()
        t_deadline = time.monotonic() + self.config.scatter_timeout_s

        def rpc_one(addr: str, live: set[str],
                    deadline: float) -> list[list[tuple[str, float]]]:
            global_injector.check("leader.worker_rpc")
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                # the budget is already spent: fail locally instead of
                # shipping a batch the worker will (rightly) refuse —
                # and record nothing on the breaker (no RPC happened)
                raise DeadlineExpired(addr + ": budget spent")
            t0 = time.perf_counter()
            raw = self._scatter.post(
                addr, "/worker/process-batch", body,
                timeout=remaining, live=live,
                headers={"X-Deadline-Ms": str(int(remaining * 1e3))})
            global_metrics.observe("scatter_rpc",
                                   time.perf_counter() - t0)
            t1 = time.perf_counter()
            hit_lists = unpack_hit_lists(raw)
            global_metrics.observe("scatter_decode",
                                   time.perf_counter() - t1)
            return hit_lists

        merged, health = self._gather_merge(
            queries, rpc_one, t_deadline,
            slots=len(queries) * 2 if staged else None,
            slice_extra={"mode": mode} if staged else None)
        t0 = time.perf_counter()
        if staged:
            # fuse AFTER the per-stage global owner-merge: each stage's
            # merged map contains the union of per-worker top-ks, so
            # its rank_list is the exact global stage top-k — fusing
            # two exact lists matches the single-node oracle.
            n = len(queries)
            c = self.config
            fused: list[dict[str, float]] = []
            for i in range(n):
                if mode == "dense":
                    fused.append(merged[n + i])
                else:
                    fused.append(fuse(
                        merged[i], merged[n + i], method=fusion,
                        k=c.top_k, rrf_k=c.fusion_rrf_k,
                        w_sparse=c.fusion_weight_sparse,
                        w_dense=c.fusion_weight_dense))
            merged = fused
            global_metrics.inc("hybrid_scatter_batches")
        # one (result, health) pair per coalesced query: every caller in
        # the group shares this batch's fan-out, so each reply carries
        # this batch's marker
        out = [(self._order_merged(m), health) for m in merged]
        global_metrics.observe("scatter_merge", time.perf_counter() - t0)
        return out

    def _slice_call(self, addr: str, queries: list[str],
                    names: list[str], t_deadline: float,
                    live: set[str], trace_parent=None,
                    kind: str = "failover",
                    extra: dict | None = None
                    ) -> list[list[tuple[str, float]]]:
        """Failover / hedged read: score the ``names`` ownership slice
        on a surviving replica (one breaker-gated, retried logical
        RPC). Exact within the slice — the worker computes the full
        ranking host-side and filters, so no slice document can be
        truncated out by documents outside it.

        ``trace_parent`` parents the slice span under the scatter span
        that dispatched it (the slice pool thread has no ambient
        context); ``kind`` distinguishes a failover re-issue from a
        hedged duplicate in the trace. ``extra`` carries additional
        request fields — the staged plan's ``mode``, so a failover
        slice re-issues BOTH scoring stages the dead owner would have
        run."""
        def rpc() -> list[list[tuple[str, float]]]:
            global_injector.check("leader.replica_rpc")
            remaining = t_deadline - time.monotonic()
            if remaining <= 0:
                raise DeadlineExpired(addr + ": budget spent")
            body = json.dumps({"queries": queries,
                               "names": names,
                               **(extra or {})}).encode()
            raw = self._scatter.post(
                addr, "/worker/process-batch", body,
                timeout=remaining, live=live,
                headers={"X-Deadline-Ms": str(int(remaining * 1e3))})
            return unpack_hit_lists(raw)

        def run():
            return self.resilience.worker_call(addr, rpc,
                                               track_latency=True)

        if trace_parent is None:
            return run()
        with global_tracer.span(
                "scatter.slice", parent=trace_parent,
                attrs={"worker": addr, "kind": kind,
                       "names": len(names)}):
            return run()

    def _gather_merge(self, queries: list[str], rpc_one,
                      t_deadline: float, slots: int | None = None,
                      slice_extra: dict | None = None
                      ) -> tuple[list[dict[str, float]], dict]:
        """The scatter/merge/failover spine shared by the per-query and
        batched paths — and by every read-plane host (leader, any-node
        reads, routers).

        1. Capture the read view ONCE (:meth:`_read_placement`) and
           compute this request's OWNER ASSIGNMENT: exactly one live,
           breaker-closed replica scores each mapped document, so the
           merge is double-count-free by construction.
        2. Fan the queries out to every registered worker
           (breaker-gated, retried, deadline-propagated ``rpc_one``).
           With ``scatter_hedge_ms`` set, a laggard's ownership slice
           is speculatively re-issued to the next replica while the
           primary RPC is still outstanding.
        3. Merge epoch 0: an owner's hits are ASSIGNED (not summed);
           non-owner replica hits are dropped; names outside the view
           keep the legacy sum-merge ONLY under an authoritative map —
           a follower view drops them and degrades honestly instead
           (R copies would double-count).
        4. Failover (epoch 1): documents whose owner failed or was
           breaker-open are re-issued — only the orphaned ownership
           slice — to surviving replicas within this same request.
           Hedge results are deduped by owner epoch: if the primary
           answered after all, its epoch-0 hits win and the hedge is
           discarded.

        ``slots`` is the hit-list count each worker reply must carry
        (default ``len(queries)``; the staged hybrid plan sends
        ``2 * len(queries)`` — n sparse + n dense — and each slot is
        owner-merged independently). ``slice_extra`` rides every
        failover/hedge slice request body, so a staged plan's
        re-issued slices run the same stages the dead owner would
        have (a v2 worker ignoring it replies ``len(queries)`` lists
        and fails the slot check — honest degradation, never a
        misaligned merge).
        """
        slots = slots if slots is not None else len(queries)
        workers = self.registry.get_all_service_addresses()
        live = set(workers)
        self.resilience.prune(live)   # breakers + latency EWMAs
        # ONE view per request: owner assignment, failover backups, and
        # the reply's (epoch, generation) stamp must agree on which
        # placement world this request routed under
        pmap = self._read_placement()
        excluded = pmap.pending_moved()
        open_set = frozenset(w for w in workers
                             if self.resilience.board.is_open(w))
        view = pmap.owner_assignment(frozenset(live), open_set)
        # the scatter span this request (or its coalesced batch) is
        # running under: per-worker RPCs become CHILD spans of it, and
        # failover/hedge slices parent under it too (the pool threads
        # have no ambient context of their own). None = untraced; every
        # tracing call below no-ops.
        tparent = global_tracer.current()
        if tparent is not None and not tparent.sampled:
            tparent = None

        # workers whose 2xx reply carried X-Compute-Degraded (served
        # from the host mirror: exact scores, sick device) — a
        # per-request set, recorded on the pool thread that ran the RPC
        # (set.add is atomic under the GIL), so concurrent scatters
        # never mislabel each other
        compute_degraded: set[str] = set()

        def call(addr: str):
            # scatter RPCs feed the gray-failure latency EWMA (slow
            # worker detection is scoped to THIS path — bulk uploads
            # legitimately take minutes and must not condemn a worker)
            def run():
                r = self.resilience.worker_call(
                    addr, lambda: rpc_one(addr, live, t_deadline),
                    track_latency=True)
                if self._scatter.pop_degraded():
                    compute_degraded.add(addr)
                return r
            if tparent is None:
                return run()
            with global_tracer.span("scatter.worker", parent=tparent,
                                    attrs={"worker": addr,
                                           "queries": len(queries)}):
                return run()

        futures = {self._pool.submit(call, w): w for w in workers}

        # hedged duplicate reads (The Tail at Scale): per laggard, the
        # ownership slice goes to the next replica while the primary is
        # still in flight; the merge below dedups by owner epoch
        # the hedge delay is the LIVE knob (autopilot-tunable; equals
        # config.scatter_hedge_ms unless the autopilot moved it),
        # read once so the guard and the wait agree within a request
        hedge_ms = self.hedge_ms
        hedge_futs: dict[str, list[tuple[str, list[str], object]]] = {}
        if hedge_ms > 0 and view.owned:
            def dispatch_hedge(addr: str) -> None:
                names = view.owned.get(addr)
                if not names:
                    return
                global_injector.check("leader.hedge")
                global_metrics.inc("scatter_hedges")
                if tparent is not None:
                    tparent.event("hedge_dispatched", laggard=addr)
                for backup, ns in pmap.backups_for(
                        names, exclude={addr}, live=live,
                        avoid=open_set).items():
                    hedge_futs.setdefault(addr, []).append(
                        (backup, ns, self._slice_pool.submit(
                            self._slice_call, backup, queries, ns,
                            t_deadline, live, tparent, "hedge",
                            slice_extra)))
            hedge_laggards(dict(futures), hedge_ms / 1e3,
                           dispatch_hedge)

        ok: dict[str, list] = {}
        failed: set[str] = set()
        circuit_open = 0
        for fut, addr in futures.items():
            try:
                if addr in hedge_futs:
                    # the laggard is raced by its hedge: wait for
                    # WHICHEVER side lands first — a primary that
                    # answered right after the hedge fired must not
                    # stall behind a slower hedge slice. The primary
                    # wins whenever it made it (owner-epoch dedup);
                    # once every hedge settled it gets only a short
                    # grace. An abandoned primary that lands later
                    # still settles its breaker accounting in the pool
                    # thread; its result is simply not merged.
                    hset = {hf for _b, _ns, hf in hedge_futs[addr]}
                    pending = {fut} | hset
                    while fut in pending and len(pending) > 1:
                        remaining = t_deadline - time.monotonic() + 30.0
                        if remaining <= 0:
                            break
                        _done, pending = _fwait(
                            pending, timeout=remaining,
                            return_when=FIRST_COMPLETED)
                    hedge_ok = any(
                        hf.done() and not hf.cancelled()
                        and hf.exception() is None for hf in hset)
                    if fut.done() or hedge_ok:
                        # primary landed, or a successful hedge stands
                        # ready to supersede it after a short grace
                        hit_lists = fut.result(timeout=0.05)
                    else:
                        # every hedge FAILED (e.g. the backup's breaker
                        # is open): the hedge bought nothing — wait for
                        # the still-in-budget primary like an unhedged
                        # worker instead of abandoning a healthy reply
                        try:
                            hit_lists = fut.result(timeout=max(
                                0.0, t_deadline - time.monotonic())
                                + 30.0)
                        except (FutureTimeout, TimeoutError) as e:
                            raise RuntimeError(
                                "scatter task stalled past deadline"
                            ) from e
                else:
                    # bounded by the request deadline plus grace for
                    # the retry policy's backoff sleeps (lockgraph
                    # indefinite-wait audit: a hung pool task must not
                    # wedge the scatter thread forever). Re-raised as a
                    # plain failure so it is NOT mistaken for a hedge
                    # win below.
                    try:
                        hit_lists = fut.result(timeout=max(
                            0.0, t_deadline - time.monotonic()) + 30.0)
                    except (FutureTimeout, TimeoutError) as e:
                        raise RuntimeError(
                            "scatter task stalled past deadline") from e
            except (FutureTimeout, TimeoutError):
                failed.add(addr)
                won = any(
                    hf.done() and not hf.cancelled()
                    and hf.exception() is None
                    for _b, _ns, hf in hedge_futs.get(addr, ()))
                if won:
                    global_metrics.inc("scatter_hedge_wins")
                    if tparent is not None:
                        tparent.event("hedge_win", laggard=addr)
                    log.info("hedge superseded laggard primary",
                             worker=addr)
                else:
                    # every hedge failed too: this is a plain scatter
                    # failure, not a win — keep the metrics honest
                    global_metrics.inc("scatter_failures")
                    log.warning("laggard primary abandoned with no "
                                "successful hedge", worker=addr)
                continue
            except CircuitOpenError:
                # fast-failed without an RPC: the worker's breaker is
                # open — counted separately so the health marker can
                # distinguish "skipped sick worker" from "RPC failed"
                circuit_open += 1
                failed.add(addr)
                global_metrics.inc("scatter_circuit_open")
                continue
            except Exception as e:
                # per-worker tolerance (Leader.java:67-69) — a reply
                # that fails wire validation degrades exactly like a
                # failed RPC; failover below recovers the mapped slice.
                # A poison verdict (the worker named the guilty query
                # rows in X-Poison-Fingerprints) is blamed per-worker
                # into the quarantine BEFORE failover re-issues the
                # slice: the re-issue may kill the backup's device too,
                # and its blame (a DISTINCT replica) is what crosses
                # the quarantine threshold — stopping the
                # query-of-death march before a third replica dies.
                for fp in getattr(e, "poison_fps", ()):
                    self.quarantine.note_fault(fp, addr)
                failed.add(addr)
                global_metrics.inc("scatter_failures")
                log.warning("worker failed during search", worker=addr,
                            err=repr(e))
                continue
            if len(hit_lists) != slots:
                failed.add(addr)
                global_metrics.inc("scatter_failures")
                log.warning("batch reply length mismatch", worker=addr)
                continue
            ok[addr] = hit_lists

        # ---- merge, epoch 0: owner hits (+ legacy sum for unmapped
        # names on the authoritative leader ONLY) ----
        owner = view.owner
        legacy_addrs: set[str] = set()   # workers with unmapped hits
        # merge policy derived from the CAPTURED view, never from a
        # fresh _read_placement(): a role flip mid-request (worker
        # promoted while this scatter is in flight) must not re-enable
        # the legacy sum-merge on a merge that ROUTED under a follower
        # view — with R replicas that sum silently double-counts, the
        # exact failure the view split exists to prevent
        sum_unmapped = not isinstance(pmap, PlacementFollower)
        dropped = 0
        merged: list[dict[str, float]] = [{} for _ in range(slots)]
        for addr, hit_lists in ok.items():
            skip = excluded.get(addr)
            for m, hits in zip(merged, hit_lists):
                for name, score in hits:
                    own = owner.get(name)
                    if own is not None:
                        if own == addr:
                            # exactly one owner scores each mapped doc:
                            # assignment — the sum-merge cannot double-
                            # count replicas by construction
                            m[name] = float(score)
                        elif skip is not None and name in skip:
                            # pending-reconcile copy on a rejoiner,
                            # already structurally ignored — counted so
                            # operators see the exclusion is active
                            global_metrics.inc("scatter_hits_excluded")
                        continue
                    if skip is not None and name in skip:
                        # unmapped pending-reconcile copy: the
                        # survivor's copy already counts (ADVICE r5)
                        global_metrics.inc("scatter_hits_excluded")
                        continue
                    if not sum_unmapped:
                        # follower-view merge: a name outside the view
                        # (uploaded after this view was read, or the
                        # view is behind) CANNOT be merged safely — with
                        # R replicas each echoing it, the legacy sum
                        # would silently double-count. Drop it and let
                        # the degraded marker say the results may be
                        # incomplete; the next view refresh heals it.
                        dropped += 1
                        continue
                    legacy_addrs.add(addr)
                    m[name] = m.get(name, 0.0) + float(score)
        if dropped:
            global_metrics.inc("router_unmapped_hits_dropped", dropped)

        # ---- failover, epoch 1: re-issue orphaned ownership slices ----
        orphans = [n for n, w in owner.items() if w in failed]
        recovered: set[str] = set()
        if orphans:
            orphan_set = set(orphans)
            failed_backups: set[str] = set()

            def consume_slice(backup: str, ns: list[str], fut) -> None:
                try:
                    hit_lists = fut.result(timeout=max(
                        0.0, t_deadline - time.monotonic()) + 30.0)
                except Exception as e:
                    # replica-distinct poison blame: a backup whose
                    # device ALSO died on the re-issued slice is the
                    # second independent witness the quarantine needs
                    for fp in getattr(e, "poison_fps", ()):
                        self.quarantine.note_fault(fp, backup)
                    failed_backups.add(backup)
                    global_metrics.inc("scatter_failover_failures")
                    log.warning("failover slice failed", worker=backup,
                                names=len(ns), err=repr(e))
                    return
                if len(hit_lists) != slots:
                    failed_backups.add(backup)
                    global_metrics.inc("scatter_failover_failures")
                    return
                ns_set = set(ns) & orphan_set
                for m, hits in zip(merged, hit_lists):
                    for name, score in hits:
                        # owner-epoch dedup: only docs whose owner
                        # actually failed, first slice writer wins
                        if name in ns_set and name not in m:
                            m[name] = float(score)
                recovered.update(ns_set)

            # phase 1 — hedges already in flight for failed primaries
            # ARE the failover slices: consume their OUTCOMES first
            for laggard, entries in hedge_futs.items():
                if laggard not in failed:
                    continue   # primary answered: epoch-0 wins
                for backup, ns, fut in entries:
                    if backup in failed:
                        continue
                    consume_slice(backup, ns, fut)
            # phase 2 — anything a hedge did NOT actually deliver
            # (never dispatched, or the hedge itself failed) gets a
            # fresh slice to the next usable replica: a failed hedge
            # must not suppress re-issue to a remaining live one
            fresh = [n for n in orphans if n not in recovered]
            if fresh:
                fresh_pending = [
                    (backup, ns, self._slice_pool.submit(
                        self._slice_call, backup, queries, ns,
                        t_deadline, live, tparent, "failover",
                        slice_extra))
                    for backup, ns in pmap.backups_for(
                        fresh, exclude=failed | failed_backups,
                        live=live, avoid=open_set).items()]
                for backup, ns, fut in fresh_pending:
                    consume_slice(backup, ns, fut)

        dark = len(view.dark) + len([n for n in orphans
                                     if n not in recovered])
        # a failed worker OUTSIDE the placement view may hold documents
        # the view cannot fail over — stay honest and mark degraded.
        # Same when unmapped documents are in play: legacy sum-merge
        # hits flowing THIS request, or a failed worker that has EVER
        # served unmapped hits (its copies may have been the only ones,
        # so their absence right now proves nothing).
        now = time.monotonic()
        for a in legacy_addrs:
            self._legacy_hit_workers[a] = now
        uncovered_workers = sum(1 for w in failed
                                if w not in view.replica_workers)
        if failed and (legacy_addrs
                       or any(w in self._legacy_hit_workers
                              for w in failed)):
            uncovered_workers += 1
        # staleness verdict from the SAME captured view the request
        # routed under (a promotion mid-request must not strip the
        # marker off a merge that actually ran against a stale view)
        sus = getattr(pmap, "suspect", None)
        health = self._record_scatter_health(
            len(workers), len(ok), circuit_open,
            failovers=len(recovered), dark=dark,
            uncovered_workers=uncovered_workers,
            dropped=dropped,
            stale_view=1 if (sus is not None and sus()) else 0)
        epoch, gen = self._view_stamp(pmap)
        health["route_epoch"] = epoch
        health["route_gen"] = gen
        # compute-plane degradation is a SEPARATE axis from result
        # degradation: a host-fallback reply is complete and exact
        # (bit-compared against the device path), just slower — the
        # `degraded` marker above stays about result completeness,
        # and this count lets the handler stamp X-Compute-Degraded
        # honestly without conflating the two
        health["compute_degraded"] = sum(
            1 for w in compute_degraded if w in ok)
        if health["compute_degraded"]:
            global_metrics.inc("scatter_compute_degraded")
        if tparent is not None:
            # the request story's verdict, on the scatter span itself:
            # chaos suites assert degraded/failover counts from here
            tparent.event("scatter.health", **{
                k: v for k, v in health.items() if v is not None})
        return merged, health

    # ---- mutation forwarding: writes stay on the elected leader ----

    def leader_url(self) -> str | None:
        """The elected leader's published address (``/leader_info``),
        cached briefly — the read plane must not pay one coordination
        read per proxied write."""
        now = time.monotonic()
        ts, cached = self._leader_cache
        if cached is not None and now - ts < 1.0:
            return cached
        try:
            addr = read_leader_info(self.coord)
        except Exception:
            return cached   # unreachable coordinator: last known
        self._leader_cache = (now, addr)
        return addr

    def proxy_write(self, path: str, body: bytes,
                    headers: dict[str, str]
                    ) -> tuple[int, bytes, dict]:
        """Forward one front-door mutation to the elected leader.
        Returns ``(status, body, reply headers)`` — non-2xx leader
        replies (sheds, 4xx rejections) are RELAYED, not raised, so
        the client sees the leader's own verdict. Raises RuntimeError
        when no leader is published (mid-election)."""
        from tfidf_tpu.cluster.node import http_post

        leader = self.leader_url()
        if not leader:
            raise RuntimeError("no leader known")
        global_injector.check("router.write_proxy")
        ctype = headers.pop("Content-Type", "application/json")

        def rpc() -> bytes:
            # NO retry: the proxied mutation is the CLIENT's to retry
            # (an upload re-sent by the proxy could double-apply if
            # the first attempt reached the leader) — the breaker
            # still records leader health across proxied writes
            return http_post(leader + path, body, content_type=ctype,
                             timeout=300.0, headers=headers,
                             origin=self.url)

        try:
            out = self.resilience.worker_call(leader, rpc, retry=False)
        except urllib.error.HTTPError as e:
            payload = e.read() or b""
            global_metrics.inc("router_writes_proxied")
            return e.code, payload, dict(e.headers)
        global_metrics.inc("router_writes_proxied")
        return 200, out, {"Content-Type": "application/json"}


def _linger_bounds(min_ms: float, max_ms: float) -> dict:
    """Coalescer adaptive-linger kwargs from config (negative = keep
    the fixed linger; see Config.batch_linger_min_ms)."""
    if min_ms < 0 or max_ms < 0:
        return {}
    return {"linger_min_s": min_ms / 1e3, "linger_max_s": max_ms / 1e3}


def _parse_multipart(body: bytes, content_type: str
                     ) -> tuple[str | None, bytes]:
    """Extract (filename, payload) from a multipart/form-data body — the
    reference accepts Spring ``MultipartFile`` uploads (``Leader.java:153``,
    ``Worker.java:125``); this keeps ``curl -F file=@doc.txt`` working."""
    msg = email.parser.BytesParser(policy=email.policy.default).parsebytes(
        b"Content-Type: " + content_type.encode() + b"\r\n\r\n" + body)
    for part in msg.iter_parts():
        fn = part.get_filename()
        if fn is not None:
            return fn, part.get_payload(decode=True) or b""
    return None, b""


class _PlaneServer(ThreadingHTTPServer):
    daemon_threads = True
    # the socketserver default backlog (5) refuses connections under a
    # concurrent-client burst; a node serves many clients at once
    request_queue_size = 256


class _HttpHandlerBase(BaseHTTPRequestHandler):
    """HTTP plumbing + the read-plane routes shared by the node handler
    (``cluster/node.py``) and the router handler below: reply framing,
    admission prologue, trace spans, the ``/leader/start`` search
    branch, the streaming download copier, and the metrics/trace
    exposition endpoints. ``self.node`` is the hosting read plane."""

    node: ScatterReadPlane   # bound by the host's __init__
    protocol_version = "HTTP/1.1"
    # the handler's wfile is unbuffered (wbufsize=0): status line, each
    # header, and the body go out as separate small writes — with Nagle
    # on, write N+1 can stall behind the peer's delayed ACK of write N
    disable_nagle_algorithm = True

    def log_message(self, fmt, *args):
        pass

    # ---- plumbing ----

    def _send(self, code: int, body: bytes,
              ctype: str = "application/json",
              headers: dict[str, str] | None = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        headers = headers or {}
        for k, v in headers.items():
            self.send_header(k, v)
        # every response produced inside a request span carries its
        # trace id — uploads, deletes, downloads, and 429 sheds
        # included, not just /leader/start (the documented contract:
        # any /leader/* reply's X-Trace-Id keys `tfidf_tpu trace`)
        if TRACE_HEADER not in headers:
            sp = global_tracer.current()
            if sp is not None:
                self.send_header(TRACE_HEADER, sp.trace_id)
        # every reply declares this binary's wire-protocol version
        # (cluster/protover.py) so either side of any exchange can
        # detect skew; the protocol witness pins the stamp
        if PROTO_HEADER not in headers:
            self.send_header(PROTO_HEADER, str(PROTO_VERSION))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, obj, code: int = 200,
              headers: dict[str, str] | None = None) -> None:
        self._send(code, json.dumps(obj).encode(), headers=headers)

    def _text(self, s: str, code: int = 200) -> None:
        self._send(code, s.encode(), "text/plain; charset=utf-8")

    def _body(self) -> bytes:
        n = int(self.headers.get("Content-Length", "0"))
        return self.rfile.read(n) if n else b""

    def _query_param(self, u, name: str) -> str | None:
        vals = urllib.parse.parse_qs(u.query).get(name)
        return vals[0] if vals else None

    def _read_upload(self, u) -> tuple[str | None, bytes]:
        body = self._body()
        ctype = self.headers.get("Content-Type", "")
        if ctype.startswith("multipart/form-data"):
            return _parse_multipart(body, ctype)
        return self._query_param(u, "name"), body

    # ---- tracing plumbing (utils/tracing.py) ----

    def _remote_ctx(self, trusted: bool):
        """The propagated trace context from the request headers, or
        None for an untraced request. ``trusted`` distinguishes the
        leader→worker continuation (sampling decided upstream) from
        front-door headers (subject to this node's own draw)."""
        return remote_context(self.headers.get(TRACE_HEADER),
                              self.headers.get(SPAN_HEADER),
                              trusted=trusted)

    @contextlib.contextmanager
    def _request_span(self, name: str, **attrs):
        """Span for one handled front-door request: keeps the caller's
        trace id when headers are present (UNTRUSTED — recording still
        subject to this node's sampling draw), else mints a new ROOT
        trace — the admission point where every client request's
        trace id is born. The span is remembered on the handler so the
        outer 500 path can still stamp the reply/log with the trace id
        AFTER the contextvar is reset (failed requests are the ones
        operators most need to trace)."""
        with global_tracer.span(
                name, parent=self._remote_ctx(trusted=False),
                attrs=attrs or None) as sp:
            self._last_span = sp
            yield sp

    def _worker_span(self, name: str, **attrs):
        """Worker-endpoint span: created ONLY when the caller sent a
        trace context (the leader's propagated scatter — trusted, the
        sampling decision was made at the root). External/reference
        clients (and local benches) hitting /worker/* directly stay
        untraced — the worker plane adds zero per-request tracing cost
        unless the leader asked."""
        ctx = self._remote_ctx(trusted=True)
        if ctx is None:
            return contextlib.nullcontext()
        return global_tracer.span(name, parent=ctx, attrs=attrs or None)

    @contextlib.contextmanager
    def _admitted(self, name: str, default_lane: str):
        """The front-door prologue every /leader/* handler shares:
        resolve the client lane, open the request span, admit-or-shed
        BEFORE the body is read or any work queues. Yields
        ``(span, lane)`` when admitted; ``(None, lane)`` when the shed
        reply was already sent (the caller just returns)."""
        client, lane = self._client_lane(default_lane)
        with self._request_span(name, lane=lane) as sp:
            decision = self.node.admission.admit(client, lane)
            if not decision.admitted:
                self._shed(decision)
                yield None, lane
            else:
                yield sp, lane

    def _deadline_header(self) -> float | None:
        """``X-Deadline-Ms`` (the leader's remaining scatter budget) as
        a local monotonic deadline; None when absent or malformed."""
        dl = self.headers.get("X-Deadline-Ms")
        if dl is None:
            return None
        try:
            return time.monotonic() + float(dl) / 1e3
        except ValueError:
            return None

    def _past_deadline(self) -> bool:
        """Refuse (504 + ``X-Deadline-Exceeded``) when the propagated
        budget is already spent; True when the reply was sent. The
        refusal is emitted inside a worker span when the caller sent a
        trace context, so even a pre-dispatch 504 carries X-Trace-Id
        and the refusal shows up in the leader's request story (the
        protocol witness pins traced-reply stamping on the worker
        plane)."""
        d = self._deadline_header()
        if d is not None and time.monotonic() > d:
            global_metrics.inc("worker_deadline_refusals")
            with self._worker_span("worker.deadline_refusal"):
                self._send(504, b"deadline exceeded",
                           "text/plain; charset=utf-8",
                           headers={"X-Deadline-Exceeded": "1"})
            return True
        return False

    # ---- wire-protocol versioning (cluster/protover.py) ----

    def _proto_gate(self, path: str) -> bool:
        """The compat-window gate on the data planes. ``/leader/*`` and
        ``/worker/*`` requests declaring a wire version below
        ``proto_min_compat`` are answered with the DISTINCT status 426
        + ``X-Proto-Rejected: 1`` — non-retryable and never a worker
        fault (cluster/resilience.py ``is_proto_rejection``), so
        rolling-upgrade skew surfaces honestly instead of tripping
        breakers. A request with no version header is implicitly
        version 1 (the pre-versioning wire); versions newer than ours
        always pass (forward compatibility). Ops endpoints
        (``/api/*``, metrics, traces) are deliberately ungated — an
        operator can inspect any node whatever binary it runs. Returns
        True when dispatch may proceed; False when the rejection reply
        was already sent."""
        # namespace compare, NOT path.startswith("/leader/"): a
        # startswith literal in a handler method would register as a
        # prefix ROUTE in the graftcheck endpoint extraction and make
        # the whole namespace "explained" — the gate is not a route
        ns = path.split("/", 2)[1] if path.startswith("/") else ""
        if ns not in ("leader", "worker"):
            return True
        peer = parse_version(self.headers.get(PROTO_HEADER))
        if in_window(peer, self.node.config.proto_min_compat):
            return True
        global_metrics.inc("proto_rejections")
        self._send(PROTO_STATUS,
                   json.dumps({
                       "error": "wire-protocol version outside the "
                                "compat window",
                       "declared": peer,
                       "min_compat": self.node.config.proto_min_compat,
                       "server_version": PROTO_VERSION}).encode(),
                   headers={PROTO_REJECTED_HEADER: "1"})
        return False

    # ---- admission plumbing (cluster/admission.py) ----

    def _client_lane(self, default_lane: str) -> tuple[str, str]:
        """(client id, lane) for admission: the ``X-Client-Id`` header
        (falling back to the peer IP) and the ``X-Priority`` header
        (``bulk`` selects the bulk lane; anything else keeps the
        endpoint's default)."""
        client = self.headers.get("X-Client-Id") or self.client_address[0]
        prio = (self.headers.get("X-Priority") or "").strip().lower()
        lane = LANE_BULK if prio == "bulk" else (
            LANE_INTERACTIVE if prio == "interactive" else default_lane)
        return client, lane

    def _shed(self, decision) -> None:
        """The explicit shed path: 429 + ``Retry-After``. The header
        carries RFC 9110 delta-seconds (an integer — fractional values
        are rejected or silently dropped by standards-compliant
        clients), rounded UP so an obedient client is never early; the
        JSON body's ``retry_after_s`` keeps the precise time-to-next-
        token the rate-limit path computed. ``Connection: close`` is
        explicit — the request body may be undrained, and a shedding
        node must not hold keep-alive state for a client it just told
        to go away (the header also tells pooled clients to drop the
        connection instead of tripping over the server-side close).
        The request body is drained up to a 1 MB cap first: closing
        with unread data in the receive queue sends RST, which can
        discard the 429 still in the client's buffer — the client
        would see ECONNRESET, classify it transient, and retry with
        no Retry-After floor, the exact hammering the shed exists to
        stop. Beyond the cap the connection closes anyway (a shedding
        node cannot hold the line for an arbitrarily large upload)."""
        self.close_connection = True
        try:
            remaining = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            remaining = 0
        remaining = min(remaining, 1 << 20)
        while remaining > 0:
            chunk = self.rfile.read(min(remaining, 1 << 16))
            if not chunk:
                break
            remaining -= len(chunk)
        body = json.dumps({"error": "overloaded",
                           "reason": decision.reason,
                           "retry_after_s": round(
                               decision.retry_after_s, 3)}).encode()
        self._send(429, body, headers={
            "Retry-After": str(math.ceil(max(decision.retry_after_s,
                                             0.0))),
            "Connection": "close",
            "X-Shed-Reason": decision.reason})

    def _read_query(self) -> str:
        """The search query: accept raw text (the reference POSTs the bare
        query string, ``Leader.java:54-59``) or ``{"query": ...}`` JSON."""
        body = self._body().decode("utf-8", "replace")
        # only attempt JSON when the body can be JSON — this is the
        # per-request hot path, and a raised-and-caught JSONDecodeError
        # per query is measurable at thousands of q/s. Strip leading
        # whitespace first: json.loads tolerates it, so the gate must too
        if body[:1].isspace():
            body = body.lstrip()
        if body[:1] in ('{', '"'):
            try:
                obj = json.loads(body)
                if isinstance(obj, dict) and "query" in obj:
                    return str(obj["query"])
                if isinstance(obj, str):
                    return obj
            except json.JSONDecodeError:
                pass
        return body

    def _read_search_request(self) -> tuple[str, str, str | None]:
        """Query plus retrieval plan for ``/leader/start``: JSON bodies
        may carry ``mode`` (``sparse`` | ``dense`` | ``hybrid``) and
        ``fusion`` (``rrf`` | ``wsum``) beside ``query``. Raw-text
        bodies and absent fields mean ``mode=sparse`` — the field is
        additive, so a v2 client's request is exactly a sparse request
        (cluster/protover.py history, wire v3). Values are returned
        unvalidated; ``_serve_search`` rejects unknown ones with 400."""
        body = self._body().decode("utf-8", "replace")
        if body[:1].isspace():
            body = body.lstrip()
        if body[:1] in ('{', '"'):
            try:
                obj = json.loads(body)
                if isinstance(obj, dict) and "query" in obj:
                    fusion = obj.get("fusion")
                    return (str(obj["query"]),
                            str(obj.get("mode") or "sparse"),
                            str(fusion) if fusion is not None else None)
                if isinstance(obj, str):
                    return obj, "sparse", None
            except json.JSONDecodeError:
                pass
        return body, "sparse", None

    # ---- shared read-plane routes ----

    def _serve_search(self) -> None:
        """The ``/leader/start`` branch, shared by the node and router
        handlers: front-door admission BEFORE any work is queued, the
        request span minted at the admission point, the health-marker
        contract on the reply (degraded header + the (epoch,
        generation) route stamp), the live latency histogram, and the
        slow-query log."""
        node = self.node
        t0 = time.perf_counter()
        with self._admitted("leader.search",
                            LANE_INTERACTIVE) as (sp, lane):
            if sp is None:
                return
            query, mode, fusion = self._read_search_request()
            if mode not in ("sparse", "dense", "hybrid"):
                self._json({"error": "unknown mode",
                            "mode": mode,
                            "allowed": ["sparse", "dense", "hybrid"]},
                           code=400)
                return
            if fusion is not None and fusion not in FUSION_METHODS:
                self._json({"error": "unknown fusion method",
                            "fusion": fusion,
                            "allowed": list(FUSION_METHODS)},
                           code=400)
                return
            if mode != "sparse" and not node.config.embedding_enabled:
                self._json({"error": "dense plane disabled "
                                     "(embedding_enabled=False)",
                            "mode": mode}, code=400)
                return
            # poison-query quarantine (after plan validation — a
            # malformed request is 400, not a quarantine verdict): a
            # (query, plan) pair that killed devices on ≥ N distinct
            # replicas is refused at the front door with 422 — the
            # application-rejection class clients must not retry —
            # before any worker is touched
            fp = poison_fingerprint(query, mode)
            if node.quarantine.is_quarantined(fp):
                global_metrics.inc("poison_quarantine_hits")
                sp.set_attr("poison_quarantined", 1)
                self._json({"error": "query quarantined: repeated "
                                     "compute faults on distinct "
                                     "replicas",
                            "fingerprint": fp,
                            "retry_after_s":
                                node.config.poison_quarantine_ttl_s},
                           code=422,
                           headers={"X-Poison-Quarantined": fp})
                return
            # traffic-capture tap: every ADMITTED search lands in the
            # durable request log (query + arrival offset + lane +
            # client) when capture is armed — shed requests are
            # deliberately not captured, so a replay reproduces the
            # admitted workload, not the overload that was refused
            # (the log records the bare query; replays run sparse)
            rlog = getattr(node, "request_log", None)
            if rlog is not None:
                rlog.record(query, lane,
                            self.headers.get("X-Client-Id")
                            or self.client_address[0])
            result, health = node.leader_search_with_health(
                query, lane=lane, mode=mode, fusion=fusion)
            # degraded marker: the body stays reference-compatible
            # (name -> score); the headers say whether every live
            # worker's shard is represented, which placement world
            # routed the request, and which trace reconstructs it
            hdrs = {TRACE_HEADER: sp.trace_id}
            # staged-plan stamp (wire v3): derived from the REQUEST, not
            # from health, so cache hits stamp identically and the pinned
            # cache-hit health dict stays untouched
            if mode == "dense":
                hdrs["X-Search-Stages"] = "dense"
            elif mode == "hybrid":
                fs = fusion or node.config.fusion_method
                hdrs["X-Search-Stages"] = (
                    "sparse,dense; fusion={} w={:g}/{:g}".format(
                        fs, node.config.fusion_weight_sparse,
                        node.config.fusion_weight_dense))
            if health.get("route_epoch") is not None:
                hdrs["X-Route-Epoch"] = str(health["route_epoch"])
            if health.get("route_gen") is not None:
                hdrs["X-Route-Generation"] = str(health["route_gen"])
            if health.get("cached"):
                sp.set_attr("cached", 1)
            # compute-plane honesty, end to end: some worker served
            # its share from the host mirror (exact scores, sick
            # device) — distinct from X-Scatter-Degraded, which is
            # about result completeness
            if health.get("compute_degraded"):
                hdrs["X-Compute-Degraded"] = str(
                    health["compute_degraded"])
                sp.set_attr("compute_degraded",
                            health["compute_degraded"])
            sp.set_attr("degraded", health.get("degraded", 0))
            if health.get("degraded"):
                hdrs["X-Scatter-Degraded"] = (
                    "attempted={attempted} "
                    "responded={responded} "
                    "circuit_open={circuit_open} "
                    "failovers={failovers} dark={dark} "
                    "dropped={dropped} stale_view={stale_view}"
                    .format(failovers=health.get("failovers", 0),
                            dark=health.get("dark", 0),
                            dropped=health.get("dropped", 0),
                            stale_view=health.get("stale_view", 0),
                            **{k: health[k] for k in
                               ("attempted", "responded",
                                "circuit_open")}))
            dt = time.perf_counter() - t0
            # live front-door latency histogram: the p50/p99
            # operators (and bench.py's cross-validation) read
            global_metrics.observe("leader_search", dt)
            slow_ms = node.config.trace_slow_query_ms
            if slow_ms > 0 and dt * 1e3 >= slow_ms:
                # trace-id-keyed slow-query log: the adapter
                # stamps trace=<id> (the span is active here),
                # so this line joins with /api/trace/<id>
                global_metrics.inc("slow_queries")
                log.warning(
                    "slow query", ms=round(dt * 1e3, 1),
                    query=query[:80],
                    degraded=health.get("degraded", 0))
            self._json(result, headers=hdrs)

    def _serve_leader_download(self, u) -> None:
        """The ``/leader/download`` branch: admission (bulk lane — real
        file I/O per request, first to shed), then the host's stream
        locator (``read_download_stream``: engine + store + worker
        probe on a node; worker + leader probe on a router)."""
        with self._admitted("leader.download",
                            LANE_BULK) as (sp, _lane):
            if sp is None:
                return
            rel = urllib.parse.unquote(
                self._query_param(u, "path") or "")
            sp.set_attr("file", rel)
            try:
                got = self.node.read_download_stream(rel)
            except PermissionError:
                self._text("invalid path", 400)
                return
            if got is None:
                self._text("not found", 404)
            else:
                self._stream(*got)

    def _serve_metrics(self, u) -> bool:
        """The ``/metrics`` + ``/api/metrics`` exposition (never
        admission-controlled — the reserved observability lane).
        Returns True when the path matched and was served."""
        if u.path not in ("/api/metrics", "/metrics"):
            return False
        node = self.node
        fmt = self._query_param(u, "format")
        if u.path == "/metrics" or fmt == "prometheus":
            body = global_metrics.render_prometheus(
                extra_gauges={
                    "breaker_open_workers_now":
                        node.resilience.board.open_count()})
            self._send(body=body.encode(), code=200,
                       ctype="text/plain; version=0.0.4; "
                             "charset=utf-8")
            return True
        snap = global_metrics.snapshot()
        # live per-worker breaker states beside the counters —
        # the CLI's degraded summary reads these
        states = node.resilience.board.snapshot()
        if states:
            snap["breaker_states"] = states
        self._json(snap)
        return True

    def _serve_trace(self, u) -> bool:
        """Trace export (observability lane): ``/api/trace/<trace-id>``
        reconstructs one request's story; ``/api/trace?recent=N`` lists
        the newest finished spans; ``?format=chrome`` renders
        Chrome-trace JSON. Returns True when the path matched."""
        if not (u.path == "/api/trace"
                or u.path.startswith("/api/trace/")):
            return False
        tid = u.path[len("/api/trace/"):] \
            if u.path.startswith("/api/trace/") else \
            (self._query_param(u, "id") or "")
        if tid:
            spans = global_tracer.get_trace(tid)
        else:
            try:
                n = int(self._query_param(u, "recent") or 100)
            except ValueError:
                n = 100
            spans = global_tracer.recent(n)
        if self._query_param(u, "format") == "chrome":
            self._json(to_chrome_trace(spans))
        else:
            self._json({"trace_id": tid or None, "spans": spans})
        return True

    def _forward_write(self, u) -> None:
        """Mutations stay on the elected leader: forward the request
        verbatim (body + the client/lane/trace headers that matter) and
        relay the leader's reply — status, body, and the shed/trace
        headers a polite client acts on. 503 + Retry-After when no
        leader is reachable (unpublished mid-election, or published
        but dead behind a not-yet-expired ephemeral — a transport
        failure must not surface as a bare 500 with no backoff hint).

        ``/leader/*`` forwards pass the LOCAL admission gate (bulk
        lane) BEFORE the body is read — the admit-before-body-read
        discipline the direct path enforces: without it a flood of
        large uploads would buffer whole request bodies on a stateless
        router only for the leader to shed them; a locally shed
        forward pays at most ``_shed``'s 1 MB drain. Ops forwards
        (``/api/*``) stay un-gated, like every ops endpoint."""
        if u.path.startswith("/leader/"):
            with self._admitted("router.proxy", LANE_BULK) as (sp, _l):
                if sp is None:
                    return
                self._forward_admitted(u)
        else:
            with self._request_span("router.proxy", path=u.path):
                self._forward_admitted(u)

    def _forward_admitted(self, u) -> None:
        body = self._body()
        fwd = {}
        for h in ("Content-Type", "X-Client-Id", "X-Priority"):
            v = self.headers.get(h)
            if v:
                fwd[h] = v
        target = u.path + (f"?{u.query}" if u.query else "")
        try:
            status, rbody, rhdrs = self.node.proxy_write(
                target, body, fwd)
        except (RuntimeError, OSError) as e:
            # no leader published, leader unreachable (URLError ⊂
            # OSError), or its breaker is open (CircuitOpenError ⊂
            # RuntimeError): same honest answer — try again shortly
            self._json({"error": "leader unavailable",
                        "detail": repr(e)[:200],
                        "retry_after_s": 1.0}, 503,
                       headers={"Retry-After": "1"})
            return
        relay = {}
        for h in ("Retry-After", "X-Shed-Reason", TRACE_HEADER):
            v = rhdrs.get(h)
            if v:
                relay[h] = v
        self._send(status, rbody,
                   rhdrs.get("Content-Type", "application/json"),
                   headers=relay)

    def _fail_500(self, u, e: BaseException) -> None:
        """The shared outer failure path: the request span's contextvar
        is gone by now; the remembered span keys the error reply + log
        line so a FAILED request stays joinable with its recorded
        (error-attributed) span."""
        sp = getattr(self, "_last_span", None)
        kv = {"trace": sp.trace_id} if sp is not None else {}
        log.warning("request failed", path=u.path, err=repr(e), **kv)
        self._send(500, f"error: {e!r}".encode(),
                   "text/plain; charset=utf-8",
                   headers={TRACE_HEADER: sp.trace_id}
                   if sp is not None else None)

    _STREAM_CHUNK = 1 << 16

    def _stream(self, stream, size: int | None) -> None:
        """Chunked-copy a readable stream to the client with constant
        memory (Content-Length when known, else chunked encoding).

        Once the 200 status line is on the wire a failure can no longer
        become a 500 — writing another status line would inject bytes
        into the declared payload and hand the client a silently
        truncated-then-corrupted file. Mid-stream errors instead ABORT
        the connection (close without the terminating chunk / short of
        Content-Length), which every HTTP client detects as a transfer
        error."""
        try:
            self.send_response(200)
            self.send_header("Content-Type", "application/octet-stream")
            sp = global_tracer.current()
            if sp is not None:   # stream replies bypass _send; same
                self.send_header(TRACE_HEADER, sp.trace_id)  # contract
            self.send_header(PROTO_HEADER, str(PROTO_VERSION))
            chunked = size is None
            if chunked:
                self.send_header("Transfer-Encoding", "chunked")
            else:
                self.send_header("Content-Length", str(size))
            self.end_headers()
            try:
                while True:
                    buf = stream.read(self._STREAM_CHUNK)
                    if not buf:
                        break
                    if chunked:
                        self.wfile.write(b"%x\r\n" % len(buf))
                        self.wfile.write(buf)
                        self.wfile.write(b"\r\n")
                    else:
                        self.wfile.write(buf)
                if chunked:
                    self.wfile.write(b"0\r\n\r\n")
            except Exception as e:
                log.warning("download stream aborted mid-transfer",
                            err=repr(e))
                self.close_connection = True
        finally:
            stream.close()


class _RouterHandler(_HttpHandlerBase):
    """The stateless router's HTTP surface: the read-plane routes
    (search, download, metrics, traces) plus a pass-through proxy that
    keeps every mutation on the elected leader."""

    # front-door mutations a router forwards to the leader verbatim
    _PROXY_POSTS = frozenset({"/leader/upload", "/leader/upload-batch",
                              "/leader/delete", "/api/drain",
                              "/api/autopilot"})

    def do_GET(self) -> None:
        u = urllib.parse.urlparse(self.path)
        router = self.node
        self._last_span = None
        try:
            if not self._proto_gate(u.path):
                return
            if u.path == "/api/health":
                # the reserved observability lane: never admission-
                # controlled, never blocks on coordination (view
                # state is in-memory)
                self._json({
                    "ok": True, "role": "router",
                    "proto_version": PROTO_VERSION,
                    "placement": router.placement.view_snapshot(),
                    "scatter_queue_depth": global_metrics.get(
                        "last_router_scatter_queue_depth", 0.0),
                    "admission": router.admission.snapshot()})
            elif u.path == "/api/status":
                self._text("I am a router")
            elif u.path == "/api/services":
                self._json(router.registry.get_all_service_addresses())
            elif u.path == "/api/leader":
                self._json({"leader": router.leader_url()})
            elif u.path == "/api/router":
                self._json(router.router_snapshot())
            elif u.path == "/api/autopilot":
                # THIS router's autopilot state + decision audit (the
                # POST kill switch still proxies to the leader). Same
                # shape as the node's route, same observability-lane
                # rule: never admission-controlled.
                try:
                    n = int(self._query_param(u, "recent") or 50)
                except ValueError:
                    n = 50
                self._json({"autopilot": router.autopilot.snapshot(),
                            "decisions": router.autopilot.decisions(n)})
            elif u.path == "/api/routers":
                self._json(list_routers(router.coord))
            elif u.path == "/api/quarantine":
                # THIS router's poison-quarantine table (per-router
                # state; observability lane, never admission-controlled)
                self._json(router.quarantine.snapshot())
            elif u.path == "/leader/download":
                self._serve_leader_download(u)
            elif self._serve_metrics(u):
                pass
            elif self._serve_trace(u):
                pass
            else:
                self._text("not found", 404)
        except Exception as e:
            self._fail_500(u, e)

    def do_POST(self) -> None:
        u = urllib.parse.urlparse(self.path)
        router = self.node
        self._last_span = None
        try:
            if not self._proto_gate(u.path):
                return
            if u.path == "/leader/start":
                self._serve_search()
            elif u.path == "/api/quarantine":
                # operator override after a fix rolls out: drop every
                # verdict on THIS router (per-router state — clear each)
                self._json({"cleared": router.quarantine.clear()})
            elif u.path in self._PROXY_POSTS:
                self._forward_write(u)
            else:
                self._text("not found", 404)
        except Exception as e:
            self._fail_500(u, e)


class QueryRouter(ScatterReadPlane):
    """One stateless router: a read-plane process with no engine, no
    shard, and no authority — just the scatter spine pointed at a
    follower view of the placement znode. Kill one and nothing is
    lost; add N and the interactive front door scales ~N-fold
    (BENCH_r07)."""

    def __init__(self, config: Config | None = None, coord=None,
                 coord_factory=None) -> None:
        # the node/router transport helpers live in cluster.node;
        # imported lazily — node.py imports this module at load time
        # (the read plane is defined here), so a module-level import
        # back into node would be a cycle
        from tfidf_tpu.cluster.node import _ScatterClient

        self.config = config or Config()
        global_tracer.configure(
            max_spans=self.config.trace_ring_spans,
            sample_rate=self.config.trace_sample_rate)
        if coord is None and coord_factory is not None:
            coord = coord_factory()
        assert coord is not None, "a coordination client is required"
        self.coord = coord
        self._coord_factory = coord_factory
        coord.on_session_event(self._on_session_event)
        self._stopping = False
        # membership view ONLY: a router never registers itself as a
        # worker — it serves no shard. The watch keeps the scatter
        # target set fresh; the epoch keys coalesced batches.
        self.registry = ServiceRegistry(
            coord, on_change=self._on_membership_change)
        self._cluster_epoch = 0
        self._pool = ThreadPoolExecutor(
            max_workers=self.config.fanout_workers,
            thread_name_prefix="router-fanout")
        self._slice_pool = ThreadPoolExecutor(
            max_workers=max(4, self.config.fanout_workers // 2),
            thread_name_prefix="router-slice")
        self._scatter = _ScatterClient()
        # the read view: a follower of the durable placement znode
        # (watch-refreshed, staleness-tracked — cluster/placement.py)
        self.placement = PlacementFollower(
            name=str(self.config.port),
            refresh_ms=self.config.router_refresh_ms,
            stale_ms=self.config.router_stale_ms)
        self.placement.bind_store(lambda: self.coord)
        self.resilience = ClusterResilience(self.config)
        self.hedge_ms = float(self.config.scatter_hedge_ms)
        self._legacy_hit_workers: dict[str, float] = {}
        self._scatter_health: dict[str, int] = {}
        # per-router scatter coalescer: its OWN queue-depth gauge
        # (last_router_scatter_queue_depth) is the per-router
        # backpressure signal AND the k8s router-HPA metric. Batches
        # group by (membership epoch, view version): one coalesced
        # batch never spans a membership transition OR a placement
        # refresh — each batch maps onto exactly one world view.
        self.scatter_batcher = (Coalescer(
            self._scatter_search_batch,
            max_batch=self.config.scatter_batch,
            linger_s=self.config.scatter_linger_ms / 1e3,
            pipeline=self.config.scatter_pipeline,
            name="router_scatter",
            # (epoch, view, mode, fusion): batches stay homogeneous in
            # world view AND retrieval plan (items are (q, mode, fusion))
            group_key=lambda q: (self._cluster_epoch,
                                 self.placement.version, q[1], q[2])
            if isinstance(q, tuple) else (self._cluster_epoch,
                                          self.placement.version,
                                          "sparse", None),
            bulk_share=self.config.scatter_bulk_share,
            **_linger_bounds(self.config.scatter_linger_min_ms,
                             self.config.scatter_linger_max_ms))
            if (self.config.scatter_micro_batch
                and not self.config.unbounded_results) else None)
        # per-router admission: same watermarks as the leader's front
        # door, keyed on THIS router's coalescer depth (the max of the
        # gauge and the live backlog — the stall-proof signal, same
        # rationale as SearchNode's depth_fn)
        self.admission = AdmissionController(
            self.config,
            depth_fn=lambda: max(
                global_metrics.get("last_router_scatter_queue_depth",
                                   0.0),
                float(self.scatter_batcher.backlog())
                if self.scatter_batcher is not None else 0.0),
            name="router")
        # per-router generation-keyed result cache: the token is
        # (membership epoch, view version) — every observed placement
        # flush advances it, so staleness is bounded by the leader's
        # flush debounce + watch latency, and a suspect view bypasses
        # the cache entirely (leader_search_with_health)
        self.result_cache = (ResultCache(self.config.router_cache_entries)
                             if (self.config.router_cache_entries > 0
                                 and not self.config.unbounded_results)
                             else None)
        # traffic-capture tap (utils/storage.py RequestLog): admitted
        # /leader/start requests land in a durable replayable log when
        # the knob names a path — bench.py --replay drives load from it
        self.request_log = (_storage.RequestLog(
            self.config.replay_capture_path,
            self.config.replay_capture_max)
            if self.config.replay_capture_path else None)
        # per-router poison-query quarantine: each router learns blame
        # from its OWN scatter failures (no coordination write — a
        # query-of-death hammering one router is quarantined there;
        # other routers learn the same way if it reaches them)
        self.quarantine = PoisonQuarantine(
            after=self.config.poison_quarantine_after,
            ttl_s=self.config.poison_quarantine_ttl_s,
            max_entries=self.config.poison_quarantine_max)
        # per-router SLO autopilot (cluster/autopilot.py): the router
        # owns its OWN admission, hedge, linger, and slow-trip knobs —
        # the same live objects the leader's loop steers — so the
        # closed loop runs here too (duck-typed over the shared
        # scatter plane; the controllers never touch leader-only
        # state). Paced by its own thread because the router has no
        # reconcile sweep to ride.
        self.autopilot = Autopilot(self)
        self._autopilot_thread: threading.Thread | None = None
        self._role = "router"
        self._leader_cache: tuple[float, str | None] = (0.0, None)
        handler = type("Handler", (_RouterHandler,), {"node": self})
        self.httpd = _PlaneServer(
            (self.config.host, self.config.port), handler)
        self.port = self.httpd.server_address[1]
        self.url = f"http://{self.config.host}:{self.port}"
        self._server_thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True,
            name=f"router-{self.port}")

    # ---- read-plane policy: always the follower view ----

    def _read_placement(self) -> PlacementMap:
        return self.placement

    def df_signature(self) -> tuple[int, int]:
        """The router result cache's generation token: (membership
        epoch, placement view version). The epoch covers worker
        death/join (which shifts per-shard df); the view version
        advances on every observed placement flush — which the leader
        performs after every df-changing commit — so a cached entry
        can outlive the corpus state it saw by at most the flush
        debounce + watch latency, and never survives a refresh."""
        return (self._cluster_epoch, self.placement.version)

    def _on_membership_change(self, old, new) -> None:
        # watch-dispatch thread: hand off fast, never block
        self._cluster_epoch += 1

    # ---- session-expiry recovery ----

    def _on_session_event(self, ev) -> None:
        """Coordination session expired (a long partition or GC
        pause): the router's ephemeral registry znode and its armed
        watches died with the session. Reconnect with a fresh session
        off-thread — a router with no factory (in-process tests
        passing a client directly) just rides its periodic refresh."""
        log.warning("router coordination session expired", url=self.url)
        if self._stopping or self._coord_factory is None:
            return
        threading.Thread(target=self._rejoin, daemon=True,
                         name=f"router-rejoin-{self.port}").start()

    def _rejoin(self) -> None:
        delay = 0.2
        while not self._stopping:
            try:
                coord = self._coord_factory()
                self.coord = coord
                if getattr(coord, "origin", None) == "":
                    coord.origin = self.url
                coord.on_session_event(self._on_session_event)
                self.registry = ServiceRegistry(
                    coord, on_change=self._on_membership_change)
                self._cluster_epoch += 1
                # the placement store getter reads self.coord
                # dynamically; re-arm the data watch on the NEW
                # session and refresh at once
                self.placement._watch_armed = False
                self.placement._wake.set()
                register_router(coord, self.url)
                global_metrics.inc("router_rejoins")
                log.info("router rejoined after session expiry",
                         url=self.url)
                return
            except Exception as e:
                log.warning("router rejoin attempt failed",
                            err=repr(e))
                time.sleep(delay)
                delay = min(delay * 2, 5.0)

    # ---- lifecycle ----

    def start(self) -> "QueryRouter":
        self._server_thread.start()
        self._scatter.origin = self.url
        if getattr(self.coord, "origin", None) == "":
            self.coord.origin = self.url
        self.placement.start()
        try:
            register_router(self.coord, self.url)
        except Exception as e:
            log.warning("router registration failed", err=repr(e))
        if self.autopilot.enabled:
            self._autopilot_thread = threading.Thread(
                target=self._autopilot_loop, daemon=True,
                name=f"router-autopilot-{self.port}")
            self._autopilot_thread.start()
        global_metrics.inc("router_started")
        log.info("router started", url=self.url,
                 view=self.placement.view_snapshot())
        return self

    def _autopilot_loop(self) -> None:
        """The router's pacing thread for ``Autopilot.maybe_run`` (the
        leader rides its reconcile sweep; a router has none)."""
        while not self._stopping:
            time.sleep(0.1)
            try:
                self.autopilot.maybe_run()
            except Exception as e:
                log.warning("router autopilot pass failed", err=repr(e))

    def stop(self) -> None:
        self._stopping = True
        self.placement.stop()
        self.httpd.shutdown()
        self.httpd.server_close()
        self._pool.shutdown(wait=False)
        self._slice_pool.shutdown(wait=False)
        if self.scatter_batcher is not None:
            self.scatter_batcher.stop()
        if self.request_log is not None:
            self.request_log.close()

    # ---- downloads: probe workers, then the leader's local store ----

    def read_download_stream(self, rel: str):
        """Locate a document for ``/leader/download``: probe every live
        worker's ``/worker/download`` (first 2xx wins, breaker-gated),
        then fall back to the leader (whose own disk/store holds
        leader-local documents). Returns ``(fileobj, size|None)`` or
        None; the caller owns closing the stream."""
        # the shared streaming seam (nemesis + trace propagation);
        # lazy import — node.py imports this module at load time
        from tfidf_tpu.cluster.node import http_get_stream

        q = urllib.parse.quote(rel)
        targets = list(self.registry.get_all_service_addresses())
        leader = self.leader_url()
        probes = [(w, "/worker/download?path=") for w in targets]
        if leader:
            probes.append((leader, "/leader/download?path="))
        for base, route in probes:
            if self.resilience.board.is_open(base):
                continue   # skip sick targets; another may hold the doc
            try:
                # breaker-tracked, no retry: probing the NEXT target is
                # this loop's retry. A 404 (doc lives elsewhere) is an
                # app-level answer from a healthy peer.
                resp = self.resilience.worker_call(
                    base, lambda base=base, route=route: http_get_stream(
                        base + route + q, timeout=30.0,
                        origin=self.url),
                    retry=False)
                size = resp.headers.get("Content-Length")
                return resp, (int(size) if size is not None else None)
            except Exception:
                continue
        return None

    # ---- operator surface ----

    def router_snapshot(self) -> dict:
        """``GET /api/router``: this router's view lag + cache health
        (the CLI ``status`` routers block aggregates these)."""
        hits = global_metrics.get("cache_hits", 0)
        misses = global_metrics.get("cache_misses", 0)
        return {
            "role": "router", "url": self.url,
            "placement": self.placement.view_snapshot(),
            "membership_epoch": self._cluster_epoch,
            "cache": {
                "entries": len(self.result_cache)
                if self.result_cache is not None else 0,
                "hits": int(hits), "misses": int(misses),
                "hit_rate": round(hits / (hits + misses), 4)
                if (hits + misses) else 0.0,
            },
            "writes_proxied": int(global_metrics.get(
                "router_writes_proxied", 0)),
            "stale_responses": int(global_metrics.get(
                "router_stale_responses", 0)),
        }

"""Coordination substrate — ZooKeeper's semantics, framework-native.

The reference outsources coordination to an external ZooKeeper ensemble
(``config/ZookeeperConfig.java:11-24``) and uses exactly four of its
primitives (SURVEY.md §2, §5.8):

1. persistent znodes as namespaces (``/election``, ``/service_registry`` —
   ``LeaderElection.java:30-47``, ``ServiceRegistry.java:35-51``);
2. EPHEMERAL and EPHEMERAL_SEQUENTIAL znodes with data payloads, whose
   lifetime is the client session (``LeaderElection.java:49-55``,
   ``ServiceRegistry.java:54-64``, ``OnElectionAction.java:45-54``);
3. one-shot watches on node deletion and on a node's children
   (``LeaderElection.java:100-113``, ``ServiceRegistry.java:91-122``);
4. session timeout as the cluster failure detector (3000 ms,
   ``ZookeeperConfig.java:17``).

This module implements those four primitives directly so the framework has
no external coordination dependency:

- :class:`CoordinationCore` — the znode tree + sessions + watches, pure
  in-process data structure (also the fake for tests, SURVEY.md §4).
- :class:`CoordinationServer` — serves a core over HTTP/JSON so many node
  processes share one substrate (the "zookeeper:2181" role). Events reach
  clients via long-polling.
- :class:`CoordinationClient` / :class:`LocalCoordination` — the client
  bean (``ZookeeperConfig.zooKeeper()`` analog): same API over HTTP or
  in-process, with automatic heartbeats and watch dispatch.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, NamedTuple
from urllib.parse import parse_qs, urlparse

from tfidf_tpu.cluster.resilience import RetryPolicy
from tfidf_tpu.utils.faults import global_injector
from tfidf_tpu.utils.logging import get_logger
from tfidf_tpu.utils.metrics import global_metrics

log = get_logger("cluster.coordination")

# Event types (names follow ZooKeeper's EventType for recognizability).
NODE_CREATED = "NodeCreated"
NODE_DELETED = "NodeDeleted"
CHILDREN_CHANGED = "NodeChildrenChanged"
SESSION_EXPIRED = "SessionExpired"

PERSISTENT = "persistent"
EPHEMERAL = "ephemeral"
EPHEMERAL_SEQUENTIAL = "ephemeral_sequential"


class Event(NamedTuple):
    type: str
    path: str


class NodeExistsError(Exception):
    pass


class NoNodeError(Exception):
    pass


class _Znode:
    __slots__ = ("data", "ephemeral_owner", "seq", "children")

    def __init__(self, data: bytes = b"",
                 ephemeral_owner: int | None = None) -> None:
        self.data = data
        self.ephemeral_owner = ephemeral_owner
        self.seq = 0                      # next sequential-child counter
        self.children: dict[str, _Znode] = {}


class _Session:
    __slots__ = ("id", "last_seen", "queue", "cond", "ephemerals", "expired")

    def __init__(self, sid: int) -> None:
        self.id = sid
        self.last_seen = time.monotonic()
        self.queue: deque[Event] = deque()
        self.cond = threading.Condition()
        self.ephemerals: set[str] = set()
        self.expired = False


def _split(path: str) -> list[str]:
    parts = [p for p in path.split("/") if p]
    if not path.startswith("/") or not parts:
        raise ValueError(f"bad path {path!r}")
    return parts


class CoordinationCore:
    """The znode tree. Thread-safe; transport-agnostic.

    Watches are one-shot, exactly like ZooKeeper's: registering happens as a
    side effect of a read (``exists``/``get_children``), firing consumes the
    registration (the reference re-arms by re-reading —
    ``ServiceRegistry.java:104``, ``LeaderElection.java:75``).
    """

    def __init__(self, session_timeout_s: float = 3.0) -> None:
        self.session_timeout_s = session_timeout_s
        self._root = _Znode()
        self._lock = threading.RLock()
        self._sessions: dict[int, _Session] = {}
        self._next_sid = 1
        # (path, kind) -> set of session ids; kind: "exists" | "children"
        self._watches: dict[tuple[str, str], set[int]] = {}
        self._closed = False
        self._reaper = threading.Thread(target=self._reap_loop, daemon=True,
                                        name="coord-reaper")
        self._reaper.start()

    # ---- sessions ----

    def new_session(self) -> int:
        with self._lock:
            sid = self._next_sid
            self._next_sid += 1
            self._sessions[sid] = _Session(sid)
            return sid

    def heartbeat(self, sid: int) -> bool:
        """Refresh liveness; False if the session is gone (client must
        treat this like an expired ZooKeeper session)."""
        global_injector.check(f"coord.heartbeat.{sid}")
        with self._lock:
            s = self._sessions.get(sid)
            if s is None:
                return False
            s.last_seen = time.monotonic()
            return True

    def close_session(self, sid: int) -> None:
        with self._lock:
            self._expire_locked(sid, reason="closed")

    def expire_session(self, sid: int) -> None:
        """Force-expire (fault injection: simulates a node partition)."""
        with self._lock:
            self._expire_locked(sid, reason="forced")

    def _expire_locked(self, sid: int, reason: str) -> None:
        s = self._sessions.pop(sid, None)
        if s is None:
            return
        s.expired = True
        for path in sorted(s.ephemerals, reverse=True):
            try:
                self._delete_locked(path)
            except NoNodeError:
                pass
        for key in list(self._watches):
            self._watches[key].discard(sid)
            if not self._watches[key]:
                del self._watches[key]
        with s.cond:
            s.queue.append(Event(SESSION_EXPIRED, ""))
            s.cond.notify_all()
        log.info("session expired", sid=sid, reason=reason)

    def _reap_loop(self) -> None:
        while not self._closed:
            time.sleep(min(0.1, self.session_timeout_s / 4))
            now = time.monotonic()
            with self._lock:
                dead = [sid for sid, s in self._sessions.items()
                        if now - s.last_seen > self.session_timeout_s]
                for sid in dead:
                    self._expire_locked(sid, reason="timeout")

    def close(self) -> None:
        self._closed = True
        with self._lock:
            for sid in list(self._sessions):
                self._expire_locked(sid, reason="shutdown")

    # ---- tree ops ----

    def _resolve(self, parts: list[str]) -> _Znode:
        node = self._root
        for p in parts:
            node = node.children.get(p)
            if node is None:
                raise NoNodeError("/" + "/".join(parts))
        return node

    def create(self, sid: int, path: str, data: bytes = b"",
               mode: str = PERSISTENT) -> str:
        with self._lock:
            parts = _split(path)
            parent = self._resolve(parts[:-1])
            name = parts[-1]
            if mode == EPHEMERAL_SEQUENTIAL:
                name = f"{name}{parent.seq:010d}"
                parent.seq += 1
            if name in parent.children:
                raise NodeExistsError(path)
            owner = sid if mode in (EPHEMERAL, EPHEMERAL_SEQUENTIAL) else None
            parent.children[name] = _Znode(data, owner)
            full = "/" + "/".join(parts[:-1] + [name])
            if owner is not None:
                s = self._sessions.get(sid)
                if s is None:
                    del parent.children[name]
                    raise NoNodeError(f"session {sid} gone")
                s.ephemerals.add(full)
            parent_path = "/" + "/".join(parts[:-1]) if parts[:-1] else "/"
            self._fire(full, "exists", NODE_CREATED)
            self._fire(parent_path, "children", CHILDREN_CHANGED)
            return full

    def delete(self, sid: int, path: str) -> None:
        with self._lock:
            self._delete_locked(path)   # also clears the owner's ephemerals

    def _delete_locked(self, path: str) -> None:
        parts = _split(path)
        parent = self._resolve(parts[:-1])
        node = parent.children.pop(parts[-1], None)
        if node is None:
            raise NoNodeError(path)
        if node.ephemeral_owner is not None:
            s = self._sessions.get(node.ephemeral_owner)
            if s is not None:
                s.ephemerals.discard(path)
        parent_path = "/" + "/".join(parts[:-1]) if parts[:-1] else "/"
        self._fire(path, "exists", NODE_DELETED)
        self._fire(parent_path, "children", CHILDREN_CHANGED)

    def exists(self, sid: int, path: str, watch: bool = False) -> bool:
        with self._lock:
            try:
                self._resolve(_split(path))
                found = True
            except NoNodeError:
                found = False
            if watch:
                self._watches.setdefault((path, "exists"), set()).add(sid)
            return found

    def get_data(self, sid: int, path: str) -> bytes:
        with self._lock:
            return self._resolve(_split(path)).data

    def set_data(self, sid: int, path: str, data: bytes) -> None:
        with self._lock:
            self._resolve(_split(path)).data = data

    def get_children(self, sid: int, path: str,
                     watch: bool = False) -> list[str]:
        with self._lock:
            if path == "/":
                node = self._root
            else:
                node = self._resolve(_split(path))
            if watch:
                self._watches.setdefault((path, "children"), set()).add(sid)
            return sorted(node.children)

    # ---- watches ----

    def _fire(self, path: str, kind: str, ev_type: str) -> None:
        sids = self._watches.pop((path, kind), None)
        if not sids:
            return
        ev = Event(ev_type, path)
        for sid in sids:
            s = self._sessions.get(sid)
            if s is None:
                continue
            with s.cond:
                s.queue.append(ev)
                s.cond.notify_all()

    def poll_events(self, sid: int, timeout_s: float) -> list[Event]:
        with self._lock:
            s = self._sessions.get(sid)
        if s is None:
            return [Event(SESSION_EXPIRED, "")]
        with s.cond:
            if not s.queue:
                s.cond.wait(timeout_s)
            evs = list(s.queue)
            s.queue.clear()
            return evs


# --------------------------------------------------------------------------
# Client API (shared by in-process and HTTP transports)
# --------------------------------------------------------------------------

Watcher = Callable[[Event], None]


class _BaseCoordination:
    """Watch registration + dispatch common to both transports.

    A single dispatch thread delivers events to Python callbacks, mirroring
    ZooKeeper's single event thread (so callbacks never race each other —
    the property ``ServiceRegistry.updateAddresses``'s ``synchronized``
    defends against is preserved by construction).
    """

    def __init__(self) -> None:
        self._wlock = threading.Lock()
        # (path, kind) -> list of watchers; one-shot, popped on fire
        self._watchers: dict[tuple[str, str], list[Watcher]] = {}
        self._session_watchers: list[Watcher] = []
        self._closed = threading.Event()
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True, name="coord-dispatch")

    def start(self) -> None:
        self._dispatcher.start()

    # transport hooks -----------------------------------------------------
    def _poll(self, timeout_s: float) -> list[Event]:
        raise NotImplementedError

    # watch plumbing ------------------------------------------------------
    def _arm(self, path: str, kind: str, watcher: Watcher | None) -> None:
        if watcher is None:
            return
        with self._wlock:
            self._watchers.setdefault((path, kind), []).append(watcher)

    def on_session_event(self, watcher: Watcher) -> None:
        """Persistent (not one-shot) session-state callback — the role of
        the reference's ``Application.process`` watcher
        (``app/Application.java:49-66``)."""
        with self._wlock:
            self._session_watchers.append(watcher)

    # long-poll failure backoff: exponential with jitter, reset by any
    # successful poll — a down coordination server is retried at a
    # decaying rate instead of a fixed 10 Hz hammer
    _POLL_BACKOFF = RetryPolicy(base_delay_s=0.1, max_delay_s=2.0,
                                name="coord_poll")

    def _dispatch_loop(self) -> None:
        poll_failures = 0
        while not self._closed.is_set():
            try:
                events = self._poll(timeout_s=1.0)
                poll_failures = 0
            except Exception:
                if self._closed.is_set():
                    return
                poll_failures += 1
                global_metrics.inc("coord_poll_failures")
                time.sleep(self._POLL_BACKOFF.backoff_delay(
                    min(poll_failures, 5)))
                continue
            for ev in events:
                if ev.type == SESSION_EXPIRED:
                    # the session is gone: deliver the expiry exactly once,
                    # then terminate — further polling would spin forever on
                    # the instant "no such session" response
                    self._closed.set()
                    with self._wlock:
                        targets = list(self._session_watchers)
                    for w in targets:
                        self._safe_call(w, ev)
                    return
                kind = ("children" if ev.type == CHILDREN_CHANGED
                        else "exists")
                with self._wlock:
                    targets = self._watchers.pop((ev.path, kind), [])
                for w in targets:
                    self._safe_call(w, ev)

    @staticmethod
    def _safe_call(w: Watcher, ev: Event) -> None:
        try:
            w(ev)
        except Exception as e:  # a watcher must never kill the dispatcher
            log.warning("watcher raised", event=ev.type, path=ev.path,
                        err=repr(e))

    # public API ----------------------------------------------------------
    def create(self, path: str, data: bytes = b"",
               mode: str = PERSISTENT) -> str:
        raise NotImplementedError

    def ensure(self, path: str, data: bytes = b"") -> None:
        """Create-if-absent for persistent namespace nodes
        (``LeaderElection.initializeElectionNode``,
        ``ServiceRegistry.createServiceRegistryZnode``)."""
        try:
            self.create(path, data, PERSISTENT)
        except NodeExistsError:
            pass

    def close(self) -> None:
        self._closed.set()


class LocalCoordination(_BaseCoordination):
    """A session on an in-process :class:`CoordinationCore`.

    Used by tests (the embedded fake the reference never had, SURVEY.md §4)
    and by single-process multi-node runs where all nodes share one core.
    """

    def __init__(self, core: CoordinationCore,
                 heartbeat_interval_s: float | None = None) -> None:
        super().__init__()
        self.core = core
        self.sid = core.new_session()
        interval = (heartbeat_interval_s if heartbeat_interval_s is not None
                    else core.session_timeout_s / 4)
        self._hb = threading.Thread(target=self._hb_loop, args=(interval,),
                                    daemon=True, name="coord-heartbeat")
        self._hb.start()
        self.start()

    def _hb_loop(self, interval: float) -> None:
        # heartbeats ARE the liveness signal: a transiently failing send
        # is retried quickly (bounded, well inside the session timeout)
        # instead of waiting a whole interval and eating into the
        # failure detector's budget
        policy = RetryPolicy(max_attempts=3,
                             base_delay_s=min(0.05, interval / 4),
                             max_delay_s=interval / 2,
                             classify=lambda e: True,
                             name="coord_heartbeat")
        while not self._closed.is_set():
            time.sleep(interval)

            def send() -> bool:
                global_injector.check("coord.heartbeat_send")
                return self.core.heartbeat(self.sid)

            try:
                if not policy.call(send):
                    return   # session is gone; expiry event follows
            except Exception:
                pass   # retries exhausted: try again next interval

    def _poll(self, timeout_s: float) -> list[Event]:
        global_injector.check("coord.long_poll")
        return self.core.poll_events(self.sid, timeout_s)

    def create(self, path, data=b"", mode=PERSISTENT):
        return self.core.create(self.sid, path, data, mode)

    def delete(self, path):
        self.core.delete(self.sid, path)

    def exists(self, path, watcher: Watcher | None = None) -> bool:
        self._arm(path, "exists", watcher)
        return self.core.exists(self.sid, path, watch=watcher is not None)

    def get_data(self, path) -> bytes:
        return self.core.get_data(self.sid, path)

    def set_data(self, path, data: bytes) -> None:
        self.core.set_data(self.sid, path, data)

    def get_children(self, path, watcher: Watcher | None = None) -> list[str]:
        self._arm(path, "children", watcher)
        return self.core.get_children(self.sid, path,
                                      watch=watcher is not None)

    def close(self) -> None:
        super().close()
        try:
            self.core.close_session(self.sid)
        except Exception:
            pass


# --------------------------------------------------------------------------
# HTTP transport
# --------------------------------------------------------------------------

class _CoordHandler(BaseHTTPRequestHandler):
    core: CoordinationCore  # set by server factory
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # route to structured logger
        pass

    def _reply(self, obj: dict, code: int = 200) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:
        u = urlparse(self.path)
        if u.path == "/events":
            q = parse_qs(u.query)
            sid = int(q["session"][0])
            timeout = float(q.get("timeout", ["25"])[0])
            evs = self.core.poll_events(sid, timeout)
            self._reply({"events": [[e.type, e.path] for e in evs]})
        else:
            self._reply({"error": "not found"}, 404)

    def do_POST(self) -> None:
        n = int(self.headers.get("Content-Length", "0"))
        req = json.loads(self.rfile.read(n) or b"{}")
        op = req.get("op")
        sid = req.get("session", 0)
        try:
            if op == "new_session":
                self._reply({"session": self.core.new_session(),
                             "timeout_s": self.core.session_timeout_s})
            elif op == "heartbeat":
                self._reply({"ok": self.core.heartbeat(sid)})
            elif op == "close_session":
                self.core.close_session(sid)
                self._reply({"ok": True})
            elif op == "create":
                full = self.core.create(sid, req["path"],
                                        bytes.fromhex(req.get("data", "")),
                                        req.get("mode", PERSISTENT))
                self._reply({"path": full})
            elif op == "delete":
                self.core.delete(sid, req["path"])
                self._reply({"ok": True})
            elif op == "exists":
                self._reply({"exists": self.core.exists(
                    sid, req["path"], watch=req.get("watch", False))})
            elif op == "get_data":
                self._reply(
                    {"data": self.core.get_data(sid, req["path"]).hex()})
            elif op == "set_data":
                self.core.set_data(sid, req["path"],
                                   bytes.fromhex(req.get("data", "")))
                self._reply({"ok": True})
            elif op == "get_children":
                self._reply({"children": self.core.get_children(
                    sid, req["path"], watch=req.get("watch", False))})
            else:
                self._reply({"error": f"bad op {op!r}"}, 400)
        except NodeExistsError as e:
            self._reply({"error": "node_exists", "path": str(e)}, 409)
        except NoNodeError as e:
            self._reply({"error": "no_node", "path": str(e)}, 404)


class CoordinationServer:
    """Serve a :class:`CoordinationCore` over HTTP (the ZooKeeper-server
    role at ``zookeeper.connection``, ``application.properties:2``)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 session_timeout_s: float = 3.0) -> None:
        self.core = CoordinationCore(session_timeout_s)
        handler = type("Handler", (_CoordHandler,), {"core": self.core})
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.httpd.daemon_threads = True
        self.address = f"{host}:{self.httpd.server_address[1]}"
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True, name="coord-server")

    def start(self) -> "CoordinationServer":
        self._thread.start()
        log.info("coordination server up", address=self.address)
        return self

    def close(self) -> None:
        self.core.close()
        self.httpd.shutdown()
        self.httpd.server_close()


class CoordinationClient(_BaseCoordination):
    """HTTP client session — the ``ZooKeeper`` client-bean analog
    (``config/ZookeeperConfig.java:15-21``)."""

    def __init__(self, address: str,
                 heartbeat_interval_s: float | None = None,
                 timeout_s: float = 5.0) -> None:
        super().__init__()
        self.base = f"http://{address}"
        self.timeout_s = timeout_s
        r = self._rpc({"op": "new_session"})
        self.sid = r["session"]
        interval = (heartbeat_interval_s if heartbeat_interval_s is not None
                    else float(r["timeout_s"]) / 4)
        self._hb = threading.Thread(target=self._hb_loop, args=(interval,),
                                    daemon=True, name="coord-heartbeat")
        self._hb.start()
        self.start()

    def _rpc(self, req: dict) -> dict:
        req.setdefault("session", getattr(self, "sid", 0))
        body = json.dumps(req).encode()
        r = urllib.request.Request(self.base + "/rpc", data=body,
                                   headers={"Content-Type":
                                            "application/json"})
        try:
            with urllib.request.urlopen(r, timeout=self.timeout_s) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as e:
            payload = json.loads(e.read() or b"{}")
            if payload.get("error") == "node_exists":
                raise NodeExistsError(payload.get("path", ""))
            if payload.get("error") == "no_node":
                raise NoNodeError(payload.get("path", ""))
            raise

    def _hb_loop(self, interval: float) -> None:
        # same discipline as LocalCoordination: retry a failed heartbeat
        # send quickly (bounded backoff) rather than burning a full
        # interval of the session-timeout budget per transient blip
        policy = RetryPolicy(max_attempts=3,
                             base_delay_s=min(0.05, interval / 4),
                             max_delay_s=interval / 2,
                             classify=lambda e: True,
                             name="coord_heartbeat")
        while not self._closed.is_set():
            time.sleep(interval)

            def send() -> bool:
                global_injector.check("coord.heartbeat_send")
                return bool(self._rpc({"op": "heartbeat"}).get("ok"))

            try:
                if not policy.call(send):
                    return   # session is gone; expiry event follows
            except Exception:
                pass  # retries exhausted: keep trying next interval

    def _poll(self, timeout_s: float) -> list[Event]:
        global_injector.check("coord.long_poll")
        url = (f"{self.base}/events?session={self.sid}"
               f"&timeout={timeout_s}")
        with urllib.request.urlopen(url, timeout=timeout_s + 5) as resp:
            payload = json.loads(resp.read())
        return [Event(t, p) for t, p in payload["events"]]

    def create(self, path, data=b"", mode=PERSISTENT):
        return self._rpc({"op": "create", "path": path, "data": data.hex(),
                          "mode": mode})["path"]

    def delete(self, path):
        self._rpc({"op": "delete", "path": path})

    def exists(self, path, watcher: Watcher | None = None) -> bool:
        self._arm(path, "exists", watcher)
        return self._rpc({"op": "exists", "path": path,
                          "watch": watcher is not None})["exists"]

    def get_data(self, path) -> bytes:
        return bytes.fromhex(self._rpc({"op": "get_data",
                                        "path": path})["data"])

    def set_data(self, path, data: bytes) -> None:
        self._rpc({"op": "set_data", "path": path, "data": data.hex()})

    def get_children(self, path, watcher: Watcher | None = None) -> list[str]:
        self._arm(path, "children", watcher)
        return self._rpc({"op": "get_children", "path": path,
                          "watch": watcher is not None})["children"]

    def close(self) -> None:
        super().close()
        try:
            self._rpc({"op": "close_session"})
        except Exception:
            pass

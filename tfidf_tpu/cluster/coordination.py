"""Coordination substrate — ZooKeeper's semantics, framework-native.

The reference outsources coordination to an external ZooKeeper ensemble
(``config/ZookeeperConfig.java:11-24``) and uses exactly four of its
primitives (SURVEY.md §2, §5.8):

1. persistent znodes as namespaces (``/election``, ``/service_registry`` —
   ``LeaderElection.java:30-47``, ``ServiceRegistry.java:35-51``);
2. EPHEMERAL and EPHEMERAL_SEQUENTIAL znodes with data payloads, whose
   lifetime is the client session (``LeaderElection.java:49-55``,
   ``ServiceRegistry.java:54-64``, ``OnElectionAction.java:45-54``);
3. one-shot watches on node deletion and on a node's children
   (``LeaderElection.java:100-113``, ``ServiceRegistry.java:91-122``);
4. session timeout as the cluster failure detector (3000 ms,
   ``ZookeeperConfig.java:17``).

This module implements those four primitives directly so the framework has
no external coordination dependency:

- :class:`CoordinationCore` — the znode tree + sessions + watches, pure
  in-process data structure (also the fake for tests, SURVEY.md §4).
- :class:`CoordinationServer` — serves a core over HTTP/JSON so many node
  processes share one substrate (the "zookeeper:2181" role). Events reach
  clients via long-polling.
- :class:`CoordinationClient` / :class:`LocalCoordination` — the client
  bean (``ZookeeperConfig.zooKeeper()`` analog): same API over HTTP or
  in-process, with automatic heartbeats and watch dispatch.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, NamedTuple
from urllib.parse import parse_qs, urlparse

from tfidf_tpu.cluster.nemesis import global_nemesis
from tfidf_tpu.cluster.protover import (PROTO_HEADER, PROTO_VERSION,
                                        proto_headers)
from tfidf_tpu.cluster.resilience import RetryPolicy
from tfidf_tpu.utils.faults import global_injector
from tfidf_tpu.utils.logging import get_logger
from tfidf_tpu.utils.metrics import global_metrics

log = get_logger("cluster.coordination")

# Event types (names follow ZooKeeper's EventType for recognizability).
NODE_CREATED = "NodeCreated"
NODE_DELETED = "NodeDeleted"
NODE_DATA_CHANGED = "NodeDataChanged"
CHILDREN_CHANGED = "NodeChildrenChanged"
SESSION_EXPIRED = "SessionExpired"

PERSISTENT = "persistent"
EPHEMERAL = "ephemeral"
EPHEMERAL_SEQUENTIAL = "ephemeral_sequential"


class Event(NamedTuple):
    type: str
    path: str


class NodeExistsError(Exception):
    pass


class NoNodeError(Exception):
    pass


class NotLeaderError(Exception):
    """Raised on a write/read addressed to an ensemble follower; carries
    the current leader's address (or None) as a failover hint."""

    def __init__(self, leader: str | None = None) -> None:
        super().__init__(f"not the ensemble leader (leader={leader})")
        self.leader = leader


class CoordinationUnavailable(Exception):
    """No quorum / commit timed out — the write was NOT acknowledged."""


class _Znode:
    __slots__ = ("data", "ephemeral_owner", "seq", "children")

    def __init__(self, data: bytes = b"",
                 ephemeral_owner: int | None = None) -> None:
        self.data = data
        self.ephemeral_owner = ephemeral_owner
        self.seq = 0                      # next sequential-child counter
        self.children: dict[str, _Znode] = {}


class _Session:
    __slots__ = ("id", "last_seen", "queue", "cond", "ephemerals", "expired")

    def __init__(self, sid: int) -> None:
        self.id = sid
        self.last_seen = time.monotonic()
        # unbounded on purpose: an evicted event would be a one-shot
        # watch fire lost forever (the registration was consumed).
        # Ensemble followers don't accumulate here — they redirect all
        # client reads, so their watch tables stay empty.
        self.queue: deque[Event] = deque()
        self.cond = threading.Condition()
        self.ephemerals: set[str] = set()
        self.expired = False


def _split(path: str) -> list[str]:
    parts = [p for p in path.split("/") if p]
    if not path.startswith("/") or not parts:
        raise ValueError(f"bad path {path!r}")
    return parts


class CoordinationCore:
    """The znode tree as a **deterministic apply-log state machine**.

    Every mutation is a command dict (JSON-serializable) routed through
    :meth:`_submit`; the default submit applies locally, and the ensemble
    layer (``cluster/ensemble.py``) overrides it to append the command to
    a replicated WAL and apply it only after quorum commit. :meth:`apply`
    is deterministic — identical command sequences produce identical
    :meth:`state_snapshot` results on every replica (the Raft state-
    machine contract). Reads, heartbeats, watches, and event queues stay
    node-local (they are not state).

    Watches are one-shot, exactly like ZooKeeper's: registering happens as a
    side effect of a read (``exists``/``get_children``), firing consumes the
    registration (the reference re-arms by re-reading —
    ``ServiceRegistry.java:104``, ``LeaderElection.java:75``).
    """

    def __init__(self, session_timeout_s: float = 3.0) -> None:
        self.session_timeout_s = session_timeout_s
        self._root = _Znode()
        self._lock = threading.RLock()
        self._sessions: dict[int, _Session] = {}
        self._next_sid = 1
        # (path, kind) -> set of session ids; kind: "exists" | "children"
        self._watches: dict[tuple[str, str], set[int]] = {}
        self._closed = False
        # mutation route: standalone applies directly; the ensemble
        # replaces this with quorum-replicated append-then-apply
        self._submit: Callable[[dict], object] = self.apply
        # session-expiry clock gate: only the ensemble LEADER may expire
        # (followers apply the leader's expire_session log entries)
        self.expiry_enabled: Callable[[], bool] = lambda: True
        self._reaper = threading.Thread(target=self._reap_loop, daemon=True,
                                        name="coord-reaper")
        self._reaper.start()

    # ---- the deterministic state machine ----

    def apply(self, cmd: dict) -> object:
        """Apply one committed command. Deterministic: same state + same
        command -> same new state and same result/exception on every
        replica. Watch/event side effects are local-only."""
        op = cmd["op"]
        with self._lock:
            if op == "create":
                return self._apply_create(
                    cmd["sid"], cmd["path"],
                    bytes.fromhex(cmd.get("data", "")),
                    cmd.get("mode", PERSISTENT))
            if op == "delete":
                self._delete_locked(cmd["path"])
                return None
            if op == "set_data":
                self._resolve(_split(cmd["path"])).data = \
                    bytes.fromhex(cmd.get("data", ""))
                # ZooKeeper semantics: a data watch set via exists()
                # fires NodeDataChanged on setData — the placement
                # follower view (cluster/placement.py) rides this.
                # Local-only side effect, like the create/delete fires
                # above: events are not replicated state.
                self._fire(cmd["path"], "exists", NODE_DATA_CHANGED)
                return None
            if op == "new_session":
                sid = self._next_sid
                self._next_sid += 1
                self._sessions[sid] = _Session(sid)
                return sid
            if op in ("close_session", "expire_session"):
                self._expire_locked(cmd["sid"],
                                    reason=cmd.get("reason", op))
                return None
            if op == "noop":        # leader-tenure marker (Raft §8)
                return None
            raise ValueError(f"unknown command {op!r}")

    def state_snapshot(self) -> dict:
        """Serialize the replicated state (tree + sessions + counters) —
        the WAL snapshot payload and the differential-test fingerprint.
        Local-only state (watches, queues, last_seen) is excluded."""
        def ser(node: _Znode) -> dict:
            return {"d": node.data.hex(), "o": node.ephemeral_owner,
                    "s": node.seq,
                    "c": {k: ser(v) for k, v in sorted(node.children.items())}}
        with self._lock:
            return {"next_sid": self._next_sid,
                    "tree": ser(self._root),
                    "sessions": {str(sid): sorted(s.ephemerals)
                                 for sid, s in self._sessions.items()}}

    def restore_state(self, state: dict) -> None:
        """Replace all replicated state (boot recovery / snapshot
        install). Restored sessions get a fresh liveness grace so
        reconnecting clients keep their ephemerals."""
        def de(obj: dict) -> _Znode:
            n = _Znode(bytes.fromhex(obj["d"]), obj["o"])
            n.seq = obj["s"]
            n.children = {k: de(v) for k, v in obj["c"].items()}
            return n
        with self._lock:
            self._root = de(state["tree"])
            self._next_sid = state["next_sid"]
            self._sessions = {}
            for sid_s, eph in state["sessions"].items():
                s = _Session(int(sid_s))
                s.ephemerals = set(eph)
                self._sessions[int(sid_s)] = s
            self._watches.clear()

    def touch_all_sessions(self) -> None:
        """Reset every session's liveness clock — called when an
        ensemble member becomes leader (or a restarted coordinator
        boots) so sessions get a full timeout to re-reach the new
        expiry clock before being declared dead."""
        with self._lock:
            now = time.monotonic()
            for s in self._sessions.values():
                s.last_seen = now

    # ---- sessions ----

    def new_session(self) -> int:
        return self._submit({"op": "new_session"})

    def heartbeat(self, sid: int) -> bool:
        """Refresh liveness; False if the session is gone (client must
        treat this like an expired ZooKeeper session). Not logged —
        liveness lives on the expiry-clock owner, not in the state."""
        global_injector.check(f"coord.heartbeat.{sid}")
        with self._lock:
            s = self._sessions.get(sid)
            if s is None:
                return False
            s.last_seen = time.monotonic()
            return True

    def close_session(self, sid: int) -> None:
        self._submit({"op": "close_session", "sid": sid,
                      "reason": "closed"})

    def expire_session(self, sid: int) -> None:
        """Force-expire (fault injection: simulates a node partition)."""
        self._submit({"op": "expire_session", "sid": sid,
                      "reason": "forced"})

    def _expire_locked(self, sid: int, reason: str) -> None:
        s = self._sessions.pop(sid, None)
        if s is None:
            return
        s.expired = True
        for path in sorted(s.ephemerals, reverse=True):
            try:
                self._delete_locked(path)
            except NoNodeError:
                pass
        for key in list(self._watches):
            self._watches[key].discard(sid)
            if not self._watches[key]:
                del self._watches[key]
        with s.cond:
            s.queue.append(Event(SESSION_EXPIRED, ""))
            s.cond.notify_all()
        log.info("session expired", sid=sid, reason=reason)

    def _reap_loop(self) -> None:
        while not self._closed:
            time.sleep(min(0.1, self.session_timeout_s / 4))
            if not self.expiry_enabled():
                continue     # ensemble follower: leader owns the clock
            now = time.monotonic()
            with self._lock:
                dead = [sid for sid, s in self._sessions.items()
                        if now - s.last_seen > self.session_timeout_s]
            for sid in dead:
                # expiry is a logged command: in ensemble mode it reaches
                # every replica through the WAL (quorum first), exactly
                # like ZooKeeper's leader-driven session expiry
                try:
                    self._submit({"op": "expire_session", "sid": sid,
                                  "reason": "timeout"})
                except Exception as e:
                    log.warning("session expiry submit failed", sid=sid,
                                err=repr(e))

    def close(self) -> None:
        self._closed = True
        with self._lock:
            for sid in list(self._sessions):
                self._expire_locked(sid, reason="shutdown")

    # ---- tree ops ----

    def _resolve(self, parts: list[str]) -> _Znode:
        node = self._root
        for p in parts:
            node = node.children.get(p)
            if node is None:
                raise NoNodeError("/" + "/".join(parts))
        return node

    def create(self, sid: int, path: str, data: bytes = b"",
               mode: str = PERSISTENT) -> str:
        return self._submit({"op": "create", "sid": sid, "path": path,
                             "data": data.hex(), "mode": mode})

    def _apply_create(self, sid: int, path: str, data: bytes,
                      mode: str) -> str:
        parts = _split(path)
        parent = self._resolve(parts[:-1])
        name = parts[-1]
        if mode == EPHEMERAL_SEQUENTIAL:
            name = f"{name}{parent.seq:010d}"
            parent.seq += 1
        if name in parent.children:
            raise NodeExistsError(path)
        owner = sid if mode in (EPHEMERAL, EPHEMERAL_SEQUENTIAL) else None
        parent.children[name] = _Znode(data, owner)
        full = "/" + "/".join(parts[:-1] + [name])
        if owner is not None:
            s = self._sessions.get(sid)
            if s is None:
                del parent.children[name]
                raise NoNodeError(f"session {sid} gone")
            s.ephemerals.add(full)
        parent_path = "/" + "/".join(parts[:-1]) if parts[:-1] else "/"
        self._fire(full, "exists", NODE_CREATED)
        self._fire(parent_path, "children", CHILDREN_CHANGED)
        return full

    def delete(self, sid: int, path: str) -> None:
        self._submit({"op": "delete", "path": path})

    def _delete_locked(self, path: str) -> None:
        parts = _split(path)
        parent = self._resolve(parts[:-1])
        node = parent.children.pop(parts[-1], None)
        if node is None:
            raise NoNodeError(path)
        if node.ephemeral_owner is not None:
            s = self._sessions.get(node.ephemeral_owner)
            if s is not None:
                s.ephemerals.discard(path)
        parent_path = "/" + "/".join(parts[:-1]) if parts[:-1] else "/"
        self._fire(path, "exists", NODE_DELETED)
        self._fire(parent_path, "children", CHILDREN_CHANGED)

    def exists(self, sid: int, path: str, watch: bool = False) -> bool:
        with self._lock:
            try:
                self._resolve(_split(path))
                found = True
            except NoNodeError:
                found = False
            if watch:
                self._watches.setdefault((path, "exists"), set()).add(sid)
            return found

    def get_data(self, sid: int, path: str) -> bytes:
        with self._lock:
            return self._resolve(_split(path)).data

    def set_data(self, sid: int, path: str, data: bytes) -> None:
        self._submit({"op": "set_data", "path": path, "data": data.hex()})

    def get_children(self, sid: int, path: str,
                     watch: bool = False) -> list[str]:
        with self._lock:
            if path == "/":
                node = self._root
            else:
                node = self._resolve(_split(path))
            if watch:
                self._watches.setdefault((path, "children"), set()).add(sid)
            return sorted(node.children)

    # ---- watches ----

    def _fire(self, path: str, kind: str, ev_type: str) -> None:
        sids = self._watches.pop((path, kind), None)
        if not sids:
            return
        ev = Event(ev_type, path)
        for sid in sids:
            s = self._sessions.get(sid)
            if s is None:
                continue
            with s.cond:
                s.queue.append(ev)
                s.cond.notify_all()

    def poll_events(self, sid: int, timeout_s: float) -> list[Event]:
        with self._lock:
            s = self._sessions.get(sid)
        if s is None:
            return [Event(SESSION_EXPIRED, "")]
        with s.cond:
            if not s.queue:
                s.cond.wait(timeout_s)
            evs = list(s.queue)
            s.queue.clear()
            return evs


# --------------------------------------------------------------------------
# Client API (shared by in-process and HTTP transports)
# --------------------------------------------------------------------------

Watcher = Callable[[Event], None]


class _BaseCoordination:
    """Watch registration + dispatch common to both transports.

    A single dispatch thread delivers events to Python callbacks, mirroring
    ZooKeeper's single event thread (so callbacks never race each other —
    the property ``ServiceRegistry.updateAddresses``'s ``synchronized``
    defends against is preserved by construction).
    """

    def __init__(self) -> None:
        self._wlock = threading.Lock()
        # (path, kind) -> list of watchers; one-shot, popped on fire
        self._watchers: dict[tuple[str, str], list[Watcher]] = {}
        self._session_watchers: list[Watcher] = []
        self._closed = threading.Event()
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True, name="coord-dispatch")

    def start(self) -> None:
        self._dispatcher.start()

    # transport hooks -----------------------------------------------------
    def _poll(self, timeout_s: float) -> list[Event]:
        raise NotImplementedError

    # watch plumbing ------------------------------------------------------
    def _arm(self, path: str, kind: str, watcher: Watcher | None) -> None:
        if watcher is None:
            return
        with self._wlock:
            self._watchers.setdefault((path, kind), []).append(watcher)

    def on_session_event(self, watcher: Watcher) -> None:
        """Persistent (not one-shot) session-state callback — the role of
        the reference's ``Application.process`` watcher
        (``app/Application.java:49-66``)."""
        with self._wlock:
            self._session_watchers.append(watcher)

    # long-poll failure backoff: exponential with jitter, reset by any
    # successful poll — a down coordination server is retried at a
    # decaying rate instead of a fixed 10 Hz hammer
    _POLL_BACKOFF = RetryPolicy(base_delay_s=0.1, max_delay_s=2.0,
                                name="coord_poll")

    def _dispatch_loop(self) -> None:
        poll_failures = 0
        while not self._closed.is_set():
            try:
                events = self._poll(timeout_s=1.0)
                poll_failures = 0
            except Exception:
                if self._closed.is_set():
                    return
                poll_failures += 1
                global_metrics.inc("coord_poll_failures")
                time.sleep(self._POLL_BACKOFF.backoff_delay(
                    min(poll_failures, 5)))
                continue
            for ev in events:
                if ev.type == SESSION_EXPIRED:
                    # the session is gone: deliver the expiry exactly once,
                    # then terminate — further polling would spin forever on
                    # the instant "no such session" response
                    self._closed.set()
                    with self._wlock:
                        targets = list(self._session_watchers)
                    for w in targets:
                        self._safe_call(w, ev)
                    return
                kind = ("children" if ev.type == CHILDREN_CHANGED
                        else "exists")
                with self._wlock:
                    targets = self._watchers.pop((ev.path, kind), [])
                for w in targets:
                    self._safe_call(w, ev)

    @staticmethod
    def _safe_call(w: Watcher, ev: Event) -> None:
        try:
            w(ev)
        except Exception as e:  # a watcher must never kill the dispatcher
            log.warning("watcher raised", event=ev.type, path=ev.path,
                        err=repr(e))

    # public API ----------------------------------------------------------
    def create(self, path: str, data: bytes = b"",
               mode: str = PERSISTENT) -> str:
        raise NotImplementedError

    def ensure(self, path: str, data: bytes = b"") -> None:
        """Create-if-absent for persistent namespace nodes
        (``LeaderElection.initializeElectionNode``,
        ``ServiceRegistry.createServiceRegistryZnode``)."""
        try:
            self.create(path, data, PERSISTENT)
        except NodeExistsError:
            pass

    def close(self) -> None:
        self._closed.set()


class LocalCoordination(_BaseCoordination):
    """A session on an in-process :class:`CoordinationCore`.

    Used by tests (the embedded fake the reference never had, SURVEY.md §4)
    and by single-process multi-node runs where all nodes share one core.
    """

    def __init__(self, core: CoordinationCore,
                 heartbeat_interval_s: float | None = None) -> None:
        super().__init__()
        self.core = core
        self.sid = core.new_session()
        interval = (heartbeat_interval_s if heartbeat_interval_s is not None
                    else core.session_timeout_s / 4)
        self._hb = threading.Thread(target=self._hb_loop, args=(interval,),
                                    daemon=True, name="coord-heartbeat")
        self._hb.start()
        self.start()

    def _hb_loop(self, interval: float) -> None:
        # heartbeats ARE the liveness signal: a transiently failing send
        # is retried quickly (bounded, well inside the session timeout)
        # instead of waiting a whole interval and eating into the
        # failure detector's budget
        policy = RetryPolicy(max_attempts=3,
                             base_delay_s=min(0.05, interval / 4),
                             max_delay_s=interval / 2,
                             classify=lambda e: True,
                             name="coord_heartbeat")
        while not self._closed.is_set():
            time.sleep(interval)

            def send() -> bool:
                global_injector.check("coord.heartbeat_send")
                return self.core.heartbeat(self.sid)

            try:
                if not policy.call(send):
                    return   # session is gone; expiry event follows
            except Exception:
                pass   # retries exhausted: try again next interval

    def _poll(self, timeout_s: float) -> list[Event]:
        global_injector.check("coord.long_poll")
        return self.core.poll_events(self.sid, timeout_s)

    def create(self, path, data=b"", mode=PERSISTENT):
        return self.core.create(self.sid, path, data, mode)

    def delete(self, path):
        self.core.delete(self.sid, path)

    def exists(self, path, watcher: Watcher | None = None) -> bool:
        self._arm(path, "exists", watcher)
        return self.core.exists(self.sid, path, watch=watcher is not None)

    def get_data(self, path) -> bytes:
        return self.core.get_data(self.sid, path)

    def set_data(self, path, data: bytes) -> None:
        self.core.set_data(self.sid, path, data)

    def get_children(self, path, watcher: Watcher | None = None) -> list[str]:
        self._arm(path, "children", watcher)
        return self.core.get_children(self.sid, path,
                                      watch=watcher is not None)

    def close(self) -> None:
        super().close()
        try:
            self.core.close_session(self.sid)
        except Exception:
            pass


# --------------------------------------------------------------------------
# HTTP transport
# --------------------------------------------------------------------------

class _CoordHandler(BaseHTTPRequestHandler):
    core: CoordinationCore  # set by server factory
    ensemble = None         # EnsembleNode when durable/replicated
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # route to structured logger
        pass

    def _reply(self, obj: dict, code: int = 200) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        # coordination plane speaks the same versioned wire as the data
        # plane so the protocol witness can assert the stamp on every
        # exchange (cluster/protover.py; the plane itself negotiates
        # nothing — /rpc is an internal seam with one client, this repo)
        self.send_header(PROTO_HEADER, str(PROTO_VERSION))
        self.end_headers()
        self.wfile.write(body)

    def _gate_leader(self) -> bool:
        """Client-facing ops are served by the ensemble leader only
        (linearizable reads + the leader-owned session/watch state);
        followers answer 421 with the leader hint so the client's
        multi-address failover can redirect."""
        ens = self.ensemble
        if ens is None or ens.is_leader():
            return True
        self._reply({"error": "not_leader", "leader": ens.leader_address()},
                    421)
        return False

    def do_GET(self) -> None:
        u = urlparse(self.path)
        if u.path == "/events":
            if not self._gate_leader():
                return
            q = parse_qs(u.query)
            sid = int(q["session"][0])
            timeout = float(q.get("timeout", ["25"])[0])
            evs = self.core.poll_events(sid, timeout)
            self._reply({"events": [[e.type, e.path] for e in evs]})
        elif u.path == "/ensemble/status":
            if self.ensemble is None:
                self._reply({"error": "no ensemble"}, 404)
            else:
                self._reply(self.ensemble.status())
        else:
            self._reply({"error": "not found"}, 404)

    def do_POST(self) -> None:
        n = int(self.headers.get("Content-Length", "0"))
        req = json.loads(self.rfile.read(n) or b"{}")
        u = urlparse(self.path)
        if u.path.startswith("/ensemble/"):
            self._ensemble_rpc(u.path, req)
            return
        if u.path != "/rpc":
            # the wire contract: client ops ride POST /rpc only. The
            # dispatch used to fall through to the op switch on ANY
            # path (graftcheck protocol endpoint-drift finding: /rpc
            # was called-but-never-served) — an unknown path must be
            # a loud 404, not a silently-served alias. The body is
            # already read above, so the keep-alive stream stays in
            # sync across the rejection.
            self._reply({"error": "not found"}, 404)
            return
        op = req.get("op")
        sid = req.get("session", 0)
        if not self._gate_leader():
            return
        try:
            if op == "new_session":
                self._reply({"session": self.core.new_session(),
                             "timeout_s": self.core.session_timeout_s})
            elif op == "heartbeat":
                self._reply({"ok": self.core.heartbeat(sid)})
            elif op == "close_session":
                self.core.close_session(sid)
                self._reply({"ok": True})
            elif op == "create":
                full = self.core.create(sid, req["path"],
                                        bytes.fromhex(req.get("data", "")),
                                        req.get("mode", PERSISTENT))
                self._reply({"path": full})
            elif op == "delete":
                self.core.delete(sid, req["path"])
                self._reply({"ok": True})
            elif op == "exists":
                self._reply({"exists": self.core.exists(
                    sid, req["path"], watch=req.get("watch", False))})
            elif op == "get_data":
                self._reply(
                    {"data": self.core.get_data(sid, req["path"]).hex()})
            elif op == "set_data":
                self.core.set_data(sid, req["path"],
                                   bytes.fromhex(req.get("data", "")))
                self._reply({"ok": True})
            elif op == "get_children":
                self._reply({"children": self.core.get_children(
                    sid, req["path"], watch=req.get("watch", False))})
            else:
                self._reply({"error": f"bad op {op!r}"}, 400)
        except NodeExistsError as e:
            self._reply({"error": "node_exists", "path": str(e)}, 409)
        except NoNodeError as e:
            self._reply({"error": "no_node", "path": str(e)}, 404)
        except NotLeaderError as e:
            self._reply({"error": "not_leader", "leader": e.leader}, 421)
        except CoordinationUnavailable as e:
            self._reply({"error": "unavailable", "detail": str(e)}, 503)

    def _ensemble_rpc(self, path: str, req: dict) -> None:
        ens = self.ensemble
        if ens is None:
            self._reply({"error": "no ensemble"}, 404)
            return
        if path == "/ensemble/vote":
            self._reply(ens.handle_vote(req))
        elif path == "/ensemble/append":
            self._reply(ens.handle_append(req))
        elif path == "/ensemble/snapshot":
            self._reply(ens.handle_install_snapshot(req))
        else:
            self._reply({"error": "not found"}, 404)


class CoordinationServer:
    """Serve a :class:`CoordinationCore` over HTTP (the ZooKeeper-server
    role at ``zookeeper.connection``, ``application.properties:2``).

    Three durability modes:

    - ``data_dir=None`` (default): in-memory standalone — the original
      substrate; state dies with the process (tests, dev).
    - ``data_dir`` set, no ``peers``: durable standalone — every write
      goes through a fsynced WAL; a crashed-and-restarted coordinator
      reconstructs the full znode tree + session table.
    - ``data_dir`` + ``peers``: replicated ensemble member (Raft-style,
      ``cluster/ensemble.py``) — a majority quorum commits every write
      before it is acknowledged; the ensemble survives the loss of any
      minority of members with zero lost acknowledged writes.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 session_timeout_s: float = 3.0,
                 data_dir: str | None = None, node_id: str = "",
                 peers: dict[str, str] | None = None,
                 election_timeout_s: float = 1.0,
                 heartbeat_interval_s: float = 0.25,
                 commit_timeout_s: float = 5.0,
                 snapshot_every: int = 512,
                 wal_fsync: bool = True) -> None:
        if peers and not data_dir:
            # never run a quorum whose hard state (term/voted_for/log)
            # evaporates on restart — that can double-vote and lose
            # acknowledged writes; refuse loudly instead of degrading
            # to a silent single in-memory coordinator
            raise ValueError("peers requires data_dir: ensemble hard "
                             "state must be durable")
        if peers and (node_id or "n0") not in peers:
            # the map must include THIS member: a node replicating to
            # its own address would depose itself on every election and
            # the quorum size would count phantom members
            raise ValueError(f"node_id {node_id or 'n0'!r} missing from "
                             f"peers map {sorted(peers)}")
        self.core = CoordinationCore(session_timeout_s)
        handler = type("Handler", (_CoordHandler,), {"core": self.core})
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.httpd.daemon_threads = True
        self.address = f"{host}:{self.httpd.server_address[1]}"
        self.ensemble = None
        if data_dir:
            from tfidf_tpu.cluster.ensemble import EnsembleNode
            nid = node_id or "n0"
            all_peers = dict(peers or {})
            my_address = all_peers.pop(nid, self.address)
            self.ensemble = EnsembleNode(
                core=self.core, data_dir=data_dir, node_id=nid,
                peers=all_peers, my_address=my_address,
                election_timeout_s=election_timeout_s,
                heartbeat_interval_s=heartbeat_interval_s,
                commit_timeout_s=commit_timeout_s,
                snapshot_every=snapshot_every, wal_fsync=wal_fsync)
            handler.ensemble = self.ensemble
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True, name="coord-server")

    def start(self) -> "CoordinationServer":
        self._thread.start()
        if self.ensemble is not None:
            self.ensemble.start()
        log.info("coordination server up", address=self.address,
                 durable=self.ensemble is not None)
        return self

    def close(self) -> None:
        if self.ensemble is not None:
            self.ensemble.close()
        self.core.close()
        self.httpd.shutdown()
        self.httpd.server_close()

    def kill(self) -> None:
        """Crash simulation: stop serving WITHOUT graceful session
        expiry or any flush beyond what appends already fsynced —
        recovery must come exclusively from the WAL + snapshot."""
        if self.ensemble is not None:
            self.ensemble.kill()
        self.core._closed = True      # stop the reaper; no expiry events
        self.httpd.shutdown()
        self.httpd.server_close()


class CoordinationClient(_BaseCoordination):
    """HTTP client session — the ``ZooKeeper`` client-bean analog
    (``config/ZookeeperConfig.java:15-21``).

    ``address`` may be a comma-separated member list (the ZooKeeper
    connect-string shape, ``"c0:2181,c1:2181,c2:2181"``). Every RPC
    fails over across members: connection failures rotate to the next
    address; a follower's 421 ``not_leader`` reply redirects straight to
    the leader hint. After a failover lands on a NEW server, all
    outstanding one-shot watches are re-armed there and compared against
    their last-read state — a change that happened during the failover
    window is delivered as a synthesized event, so watch semantics
    survive ensemble-leader loss (ZooKeeper's ``setWatches`` reconnect
    dance)."""

    def __init__(self, address: str,
                 heartbeat_interval_s: float | None = None,
                 timeout_s: float = 5.0,
                 failover_deadline_s: float = 10.0,
                 origin: str = "") -> None:
        super().__init__()
        self.addresses = [a.strip() for a in address.split(",") if a.strip()]
        assert self.addresses, "at least one coordinator address required"
        self.timeout_s = timeout_s
        # how long one logical op keeps rotating/redirecting before
        # giving up — must comfortably span an ensemble leader election
        self.failover_deadline_s = failover_deadline_s
        # this client's endpoint identity for the nemesis shim
        # (cluster/nemesis.py); SearchNode stamps its own URL here
        self.origin = origin
        # jittered, per-instance reconnect backoff for the rotate/
        # retry sleeps in _rpc and _poll: after a healed partition
        # every client would otherwise re-attempt on the same fixed
        # 20 Hz beat — a synchronized thundering herd on the freshly
        # recovered coordinator. Per-instance rng -> decorrelated
        # phases; exponential growth caps the per-client retry rate
        # while the outage lasts. (The heartbeat loop's RetryPolicy
        # below is jittered the same way by default.)
        self._reconnect = RetryPolicy(base_delay_s=0.05, max_delay_s=0.8,
                                      name="coord_reconnect")
        self._addr_lock = threading.Lock()
        self._addr_i = 0
        self._last_good: str | None = None
        # any connection-level failure since the last success: the next
        # success re-arms watches even on the SAME address (a durable
        # standalone coordinator restarts on its old host:port, and
        # restore_state wiped its server-side watch table)
        self._conn_failed = False
        # (path, kind) -> last-read value for failover re-arm comparison
        self._armed_state: dict[tuple[str, str], object] = {}
        self._synthetic: deque[Event] = deque()
        self._rearm_lock = threading.Lock()
        r = self._rpc({"op": "new_session"})
        self.sid = r["session"]
        interval = (heartbeat_interval_s if heartbeat_interval_s is not None
                    else float(r["timeout_s"]) / 4)
        self._hb = threading.Thread(target=self._hb_loop, args=(interval,),
                                    daemon=True, name="coord-heartbeat")
        self._hb.start()
        self.start()

    # ---- address failover ----

    def _current(self) -> str:
        with self._addr_lock:
            return self.addresses[self._addr_i % len(self.addresses)]

    def _advance(self) -> None:
        with self._addr_lock:
            self._addr_i = (self._addr_i + 1) % len(self.addresses)
        global_metrics.inc("coord_addr_rotations")

    def _redirect(self, leader: str | None) -> None:
        if not leader:
            self._advance()
            return
        with self._addr_lock:
            if leader not in self.addresses:
                self.addresses.append(leader)
            self._addr_i = self.addresses.index(leader)

    def _note_success(self, base: str, rearm_ok: bool = True) -> None:
        prev, self._last_good = self._last_good, base
        failed, self._conn_failed = self._conn_failed, False
        moved = prev is not None and prev != base
        if moved:
            global_metrics.inc("coord_failovers")
            log.info("failed over", frm=prev, to=base)
        if (moved or (failed and prev is not None)) and rearm_ok:
            # new server OR possible same-address restart: either way
            # the server-side watch table may no longer have our watches
            self._rearm_watches()

    # Mutations are NOT retried after an ambiguous failure (the request
    # may have been delivered and committed — re-sending an
    # EPHEMERAL_SEQUENTIAL create would mint a second znode and wedge
    # the election on an orphan candidate). Only provably-undelivered
    # failures (connection refused) and pre-execution rejections
    # (421 not_leader) are safe to retry for these ops.
    _MUTATING_OPS = frozenset(
        {"create", "delete", "set_data", "close_session"})

    def _reconnect_sleep(self, attempt: int) -> None:
        """One jittered backoff sleep before re-rotating (see
        ``_reconnect`` in ``__init__``). Routed through the policy's
        injectable ``_sleep`` so tests can record the chosen delays."""
        global_metrics.inc("coord_reconnect_backoffs")
        self._reconnect._sleep(
            self._reconnect.backoff_delay(min(max(attempt, 1), 5)))

    @staticmethod
    def _definitely_undelivered(e: Exception) -> bool:
        if isinstance(e, ConnectionRefusedError):
            return True
        return (isinstance(e, urllib.error.URLError)
                and isinstance(getattr(e, "reason", None),
                               ConnectionRefusedError))

    def _rpc(self, req: dict, _rearm: bool = True) -> dict:
        req.setdefault("session", getattr(self, "sid", 0))
        body = json.dumps(req).encode()
        mutating = req.get("op") in self._MUTATING_OPS
        deadline = time.monotonic() + self.failover_deadline_s
        last_exc: Exception = CoordinationUnavailable("no address tried")
        tries = 0
        while tries == 0 or time.monotonic() < deadline:
            tries += 1
            base = self._current()
            h = {"Content-Type": "application/json"}
            h.update(proto_headers())
            h = global_nemesis.filter_headers(self.origin, base, h)
            r = urllib.request.Request(f"http://{base}/rpc", data=body,
                                       headers=h)
            try:
                global_nemesis.check_send(self.origin, base)
                with urllib.request.urlopen(
                        r, timeout=self.timeout_s) as resp:
                    payload = json.loads(global_nemesis.filter_reply(
                        self.origin, base, resp.read()))
                self._note_success(base, _rearm)
                return payload
            except urllib.error.HTTPError as e:
                payload = json.loads(e.read() or b"{}")
                err = payload.get("error")
                if err == "node_exists":
                    self._note_success(base, _rearm)
                    raise NodeExistsError(payload.get("path", ""))
                if err == "no_node":
                    self._note_success(base, _rearm)
                    raise NoNodeError(payload.get("path", ""))
                if err == "not_leader":
                    # rejected before execution: always safe to retry
                    last_exc = e
                    self._redirect(payload.get("leader"))
                    if payload.get("leader"):
                        time.sleep(0.02)
                    else:
                        # no hint = mid-election: jittered wait so a
                        # whole cluster of clients doesn't re-poll the
                        # forming ensemble in lock-step
                        self._reconnect_sleep(tries)
                    continue
                if err == "unavailable" or e.code >= 500:
                    if err == "unavailable" and mutating:
                        # commit timeout: the entry may still commit
                        # later — surface the ambiguity, don't re-send
                        raise CoordinationUnavailable(
                            payload.get("detail", "no quorum"))
                    last_exc = e
                    self._advance()
                    self._reconnect_sleep(tries)
                    continue
                raise
            except (urllib.error.URLError, ConnectionError, OSError,
                    TimeoutError) as e:
                self._conn_failed = True
                if mutating and not self._definitely_undelivered(e):
                    raise
                last_exc = e
                self._advance()
                self._reconnect_sleep(tries)
                continue
        raise last_exc

    # ---- watch re-arm after failover ----

    def _rearm_watches(self) -> None:
        """Re-register every outstanding one-shot watch on the new
        server; if the watched state changed while we were failing
        over, deliver the missed transition as a synthesized event
        (one-shot semantics preserved: changed -> fire once; unchanged
        -> stays armed server-side)."""
        if not self._rearm_lock.acquire(blocking=False):
            return      # another thread is already re-arming
        try:
            with self._wlock:
                armed = dict(self._armed_state)
            for (path, kind), last in armed.items():
                try:
                    if kind == "exists":
                        cur: object = bool(self._rpc(
                            {"op": "exists", "path": path, "watch": True},
                            _rearm=False)["exists"])
                        ev = (Event(NODE_CREATED if cur else NODE_DELETED,
                                    path) if cur != last else None)
                    else:
                        cur = list(self._rpc(
                            {"op": "get_children", "path": path,
                             "watch": True}, _rearm=False)["children"])
                        ev = (Event(CHILDREN_CHANGED, path)
                              if cur != last else None)
                    with self._wlock:
                        if ev is not None:
                            self._armed_state.pop((path, kind), None)
                            self._synthetic.append(ev)
                        else:
                            self._armed_state[(path, kind)] = cur
                except Exception as e:
                    # leave the armed entry and re-flag the failure so
                    # the next successful op retries the re-arm — a
                    # one-shot giving up here would lose the watch
                    self._conn_failed = True
                    log.warning("watch re-arm failed", path=path,
                                kind=kind, err=repr(e))
            global_metrics.inc("coord_watch_rearms")
        finally:
            self._rearm_lock.release()

    def _hb_loop(self, interval: float) -> None:
        # same discipline as LocalCoordination: retry a failed heartbeat
        # send quickly (bounded backoff) rather than burning a full
        # interval of the session-timeout budget per transient blip
        policy = RetryPolicy(max_attempts=3,
                             base_delay_s=min(0.05, interval / 4),
                             max_delay_s=interval / 2,
                             classify=lambda e: True,
                             name="coord_heartbeat")
        while not self._closed.is_set():
            time.sleep(interval)

            def send() -> bool:
                global_injector.check("coord.heartbeat_send")
                return bool(self._rpc({"op": "heartbeat"}).get("ok"))

            try:
                if not policy.call(send):
                    return   # session is gone; expiry event follows
            except Exception:
                pass  # retries exhausted: keep trying next interval

    def _poll(self, timeout_s: float) -> list[Event]:
        global_injector.check("coord.long_poll")
        with self._wlock:
            if self._synthetic:
                evs = list(self._synthetic)
                self._synthetic.clear()
                return evs
        deadline = time.monotonic() + self.failover_deadline_s
        last_exc: Exception = CoordinationUnavailable("no address tried")
        payload = None
        tries = 0
        while tries == 0 or time.monotonic() < deadline:
            if self._closed.is_set():
                raise CoordinationUnavailable("client closed")
            tries += 1
            base = self._current()
            url = (f"http://{base}/events?session={self.sid}"
                   f"&timeout={timeout_s}")
            poll_req = urllib.request.Request(
                url, headers=global_nemesis.filter_headers(
                    self.origin, base, proto_headers()))
            try:
                global_nemesis.check_send(self.origin, base)
                with urllib.request.urlopen(
                        poll_req, timeout=timeout_s + 5) as resp:
                    payload = json.loads(global_nemesis.filter_reply(
                        self.origin, base, resp.read()))
                self._note_success(base)
                break
            except urllib.error.HTTPError as e:
                body = json.loads(e.read() or b"{}")
                if body.get("error") == "not_leader":
                    last_exc = e
                    self._redirect(body.get("leader"))
                    if body.get("leader"):
                        time.sleep(0.02)
                    else:
                        self._reconnect_sleep(tries)
                    continue
                last_exc = e
                self._advance()
                self._reconnect_sleep(tries)
            except (urllib.error.URLError, ConnectionError, OSError,
                    TimeoutError) as e:
                self._conn_failed = True
                last_exc = e
                self._advance()
                self._reconnect_sleep(tries)
        if payload is None:
            raise last_exc
        evs = [Event(t, p) for t, p in payload["events"]]
        with self._wlock:
            for ev in evs:      # a fired watch is no longer armed
                kind = ("children" if ev.type == CHILDREN_CHANGED
                        else "exists")
                self._armed_state.pop((ev.path, kind), None)
        return evs

    def create(self, path, data=b"", mode=PERSISTENT):
        return self._rpc({"op": "create", "path": path, "data": data.hex(),
                          "mode": mode})["path"]

    def delete(self, path):
        self._rpc({"op": "delete", "path": path})

    def exists(self, path, watcher: Watcher | None = None) -> bool:
        self._arm(path, "exists", watcher)
        got = bool(self._rpc({"op": "exists", "path": path,
                              "watch": watcher is not None})["exists"])
        if watcher is not None:
            with self._wlock:
                self._armed_state[(path, "exists")] = got
        return got

    def get_data(self, path) -> bytes:
        return bytes.fromhex(self._rpc({"op": "get_data",
                                        "path": path})["data"])

    def set_data(self, path, data: bytes) -> None:
        self._rpc({"op": "set_data", "path": path, "data": data.hex()})

    def get_children(self, path, watcher: Watcher | None = None) -> list[str]:
        self._arm(path, "children", watcher)
        kids = self._rpc({"op": "get_children", "path": path,
                          "watch": watcher is not None})["children"]
        if watcher is not None:
            with self._wlock:
                self._armed_state[(path, "children")] = list(kids)
        return kids

    def close(self) -> None:
        super().close()
        try:
            # best-effort goodbye: don't spend the full failover budget
            # on a coordinator that is already gone
            self.failover_deadline_s = min(self.failover_deadline_s, 1.0)
            self._rpc({"op": "close_session"})
        except Exception:
            pass

"""Closed-loop SLO autopilot: the cluster tunes its own knobs from its
live percentiles, with a full decision audit trail.

PR 9 made tail latency observable (78-bucket live histograms,
per-request traces, Prometheus exposition); until now every knob those
signals should drive was hand-tuned static config — ``deploy/k8s.yaml``
shipped a guessed ``TFIDF_SCATTER_HEDGE_MS=250``, the admission
watermarks were fixed counts, the adaptive-linger ceiling and the
gray-failure ``breaker_slow_threshold_ms`` were constants someone
typed. This module closes the loop: a leader-side control pass riding
the reconcile-sweep cadence (like the rebalancer) that each interval

- sets ``scatter_hedge_ms`` to the WINDOWED scatter-leg p95 plus an
  epsilon (hedges fire on genuine outliers, never on the body of the
  distribution, whatever that body currently is);
- scales the admission queue high/critical watermarks from the
  measured queue-depth -> ``leader_search`` p99 relationship: p99 over
  the SLO shrinks the depth the front door may queue (shed earlier),
  p99 comfortably under the SLO *while sheds happened* grows it (stop
  refusing work the cluster could absorb) — multiplicative ratio
  steering toward ``autopilot_p99_slo_ms``, the one number the
  operator still owns;
- widens/narrows the adaptive-linger ceiling from measured
  batch-formation gain vs added wait: unfilled batches while queries
  queue -> more linger buys fill; full batches -> the wait buys
  nothing, narrow it back;
- derives ``breaker_slow_threshold_ms`` from the cross-worker
  successful-call latency-EWMA spread (median x a spread multiple), so
  "slow" means *slow relative to this cluster right now*, not a
  constant guessed for some other hardware.

Every controller shares the same discipline, because a control loop
that flaps is worse than a constant:

- **clamped bounds** — each knob has a floor and a ceiling
  (``autopilot_*_floor/ceiling``); the controller can never leave
  them, no matter what the sensors claim.
- **hysteresis** — a relative dead band (``autopilot_hysteresis``):
  targets within the band of the current value cause no movement.
- **direction confirmation** — a move needs ``autopilot_confirm``
  CONSECUTIVE sweeps proposing the same direction; one noisy window
  cannot reverse a trend.
- **damping** — only ``autopilot_step`` of the remaining error is
  applied per adjustment (geometric approach, no overshoot).
- **a global kill switch** — ``autopilot_enabled`` off (statically, or
  live via ``POST /api/autopilot``) reverts every managed knob to its
  static config value INSTANTLY and stops the loop.

Because this is the observability archetype, the autopilot is itself
fully observable: a ``tfidf_autopilot_*`` gauge per managed knob
(current value, floor, ceiling, last adjustment direction), a bounded
ring of decision records — the sensor inputs read, the decision made,
the knob written — exported via ``GET /api/autopilot`` and the CLI
``autopilot`` subcommand, every applied change logged with the sensor
values that justified it, and a span (``autopilot.sweep``) with one
``knob_adjusted`` event per change on any sweep that moved a knob.

Sensors are WINDOWED: the cumulative histograms in
:mod:`tfidf_tpu.utils.metrics` are diffed between sweeps
(:class:`HistWindow`), so the controller reacts to the last control
interval, not to hours of history.
"""

from __future__ import annotations

import statistics
import threading
import time
from collections import deque
from typing import TYPE_CHECKING, Callable

from tfidf_tpu.utils.faults import global_injector
from tfidf_tpu.utils.logging import get_logger
from tfidf_tpu.utils.metrics import (BUCKET_BOUNDS_S, bucket_quantile,
                                     global_metrics)
from tfidf_tpu.utils.tracing import epoch_now, global_tracer

if TYPE_CHECKING:   # circular at runtime: node.py constructs Autopilot
    from tfidf_tpu.cluster.node import SearchNode

log = get_logger("cluster.autopilot")


def delta_quantile(counts: list[int], q: float) -> float | None:
    """Quantile estimate in SECONDS over a *delta* histogram (bucket
    counts from one window, ``len == len(BUCKET_BOUNDS_S) + 1``): the
    shared :func:`~tfidf_tpu.utils.metrics.bucket_quantile` math,
    without the observed-min/max clamp (a window has no summary
    extremes) — still within one bucket ratio of truth by
    construction."""
    return bucket_quantile(counts, sum(counts), q)


class HistWindow:
    """Windowed view over one cumulative ``global_metrics`` histogram:
    ``advance()`` returns the bucket-count DELTA since the previous
    call (the first call returns everything observed so far)."""

    def __init__(self, name: str) -> None:
        self.name = name
        self._prev: tuple[list[int], int] | None = None

    def advance(self) -> tuple[list[int], int]:
        snap = global_metrics.hist_snapshot(self.name)
        prev, self._prev = self._prev, snap
        if snap is None:
            return [0] * (len(BUCKET_BOUNDS_S) + 1), 0
        counts, n = snap
        if prev is None:
            return counts, n
        pc, pn = prev
        return [c - p for c, p in zip(counts, pc)], n - pn


class CounterWindow:
    """Delta of one cumulative counter between sweeps."""

    def __init__(self, name: str) -> None:
        self.name = name
        self._prev = 0.0

    def advance(self) -> float:
        cur = global_metrics.get(self.name, 0.0)
        d, self._prev = cur - self._prev, cur
        return d


class KnobController:
    """One managed knob: a sensor law (``sense``) plus live read/write
    accessors and the static (config) value the kill switch restores.
    The shared hysteresis/confirmation/damping discipline lives in
    :meth:`Autopilot._decide`, so every controller oscillates (or
    rather, provably does not) the same way."""

    def __init__(self, knob: str, floor: float, ceiling: float,
                 read: Callable[[], float],
                 write: Callable[[float], None],
                 static: float, integral: bool = False) -> None:
        self.knob = knob
        self.floor = float(floor)
        self.ceiling = float(max(ceiling, floor))
        self.read = read
        self.write = write
        self.static = float(static)
        self.integral = integral
        # decision state for the shared discipline
        self.pending_dir = 0     # direction awaiting confirmation
        self.confirms = 0        # consecutive sweeps proposing it
        self.last_dir = 0        # direction of the last APPLIED change
        self.smoothed: float | None = None   # EWMA-filtered target
        self.last_adjust_mono = 0.0
        self.adjustments = 0

    def quantize(self, v: float) -> float:
        return float(int(round(v))) if self.integral else round(v, 2)

    def reset(self) -> None:
        self.pending_dir = 0
        self.confirms = 0
        self.smoothed = None

    def clear_sensor_state(self) -> None:
        """Drop subclass-held sensor memory (peak-holds, calm
        counters). Called on kill-switch RE-ENABLE, where the
        documented contract is fresh windows with no stale trend —
        NOT on per-sweep reset(), where that memory is the point."""

    def revert(self) -> None:
        """Kill-switch restore. The base write path is exact for a
        single-valued knob; controllers that derive SECONDARY values
        from a write (the watermark pair) override this to restore
        every static value verbatim."""
        self.write(self.static)

    # subclasses: (target, inputs) or None when the window carries no
    # actionable signal (too few samples, no pressure, ...)
    def sense(self, frame: dict, current: float
              ) -> tuple[float, dict] | None:
        raise NotImplementedError


class HedgeController(KnobController):
    """``scatter_hedge_ms`` = windowed scatter-leg p95 + epsilon. A
    hedge should race only genuine laggards: pinned at the body of the
    distribution it would duplicate most batches' slices (roughly
    doubling steady-state load); parked far above it (the hand-tuned
    250 ms) it never fires before the tail has already happened.

    Saturation guard (The Tail at Scale's own caveat): a hedge is a
    DUPLICATE read, worth paying only while spare capacity exists to
    absorb it — under overload it amplifies the very queueing that
    made the laggard slow. While queries are queueing (the scatter
    backlog/depth signal is nonzero) the controller PARKS the hedge at
    its ceiling: in-budget tail-trimming stops, only true stalls far
    past the ceiling still get raced. The park/unpark transitions ride
    the same smoothing/hysteresis/confirmation discipline as every
    other move."""

    # unpark only after this many CONSECUTIVE pressure-free windows.
    # Parking enters through the same confirmation discipline as any
    # move (two pressure windows + damped steps toward the ceiling —
    # one noisy depth reading cannot park a healthy hedge); unparking
    # is ADDITIONALLY sticky: intermittent pressure at the saturation
    # edge still means no spare capacity for duplicates, and a
    # park/unpark cycle per pressure blip would read as flapping.
    CALM_SWEEPS = 3

    def __init__(self, cfg, read, write) -> None:
        super().__init__("scatter_hedge_ms",
                         cfg.autopilot_hedge_floor_ms,
                         cfg.autopilot_hedge_ceiling_ms,
                         read, write, cfg.scatter_hedge_ms)
        self.epsilon_ms = cfg.autopilot_hedge_epsilon_ms
        self.min_window = cfg.autopilot_min_window
        # starts satisfied: a cluster that was never under pressure
        # tracks the tail from its first window
        self._calm = self.CALM_SWEEPS

    def clear_sensor_state(self) -> None:
        self._calm = self.CALM_SWEEPS

    def sense(self, frame, current):
        if frame["depth"] > 0:
            self._calm = 0
            return self.ceiling, {
                "parked": 1, "depth": frame["depth"],
                "scatter_p95_ms": round(frame["scatter_p95_ms"], 2)}
        if self._calm < self.CALM_SWEEPS:
            self._calm += 1
            if self._calm < self.CALM_SWEEPS:
                return None   # recent pressure: stay parked, hold
        if frame["scatter_n"] < self.min_window:
            return None
        p95 = frame["scatter_p95_ms"]
        return p95 + self.epsilon_ms, {
            "scatter_p95_ms": round(p95, 2),
            "scatter_n": frame["scatter_n"],
            "epsilon_ms": self.epsilon_ms}


class WatermarkController(KnobController):
    """``admission_queue_high_water`` steered by the measured
    queue-depth -> ``leader_search`` p99 relationship: admitted p99
    over the SLO means the queue the front door tolerates is too deep
    (shrink by the p99/SLO ratio); p99 comfortably under the SLO while
    sheds happened means work was refused that would have met the SLO
    (grow by the same ratio). No sheds and p99 in budget = nothing to
    learn, hold. The critical watermark keeps the static
    critical/high ratio throughout."""

    GROW_GUARD = 0.7   # grow only when peak p99 < GROW_GUARD * slo
    PEAK_WINDOWS = 3   # peak-hold depth over recent sensor windows

    def __init__(self, cfg, read, write, revert=None) -> None:
        super().__init__("admission_queue_high_water",
                         cfg.autopilot_queue_floor,
                         cfg.autopilot_queue_ceiling,
                         read, write, cfg.admission_queue_high_water,
                         integral=True)
        self.slo_ms = cfg.autopilot_p99_slo_ms
        self.min_window = cfg.autopilot_min_window
        # peak-hold over the last few windowed p99s: an SLO is about
        # the WORST windows — under zipfian traffic most windows are
        # cache-hit-dominated and calm, and a single calm window must
        # not regrow the watermark mid-overload (that re-opens the
        # queue exactly while the tail is burning)
        self._recent_p99: deque[float] = deque(maxlen=self.PEAK_WINDOWS)
        if revert is not None:
            self.revert = revert   # exact two-value static restore

    def clear_sensor_state(self) -> None:
        self._recent_p99.clear()

    def sense(self, frame, current):
        if frame["leader_n"] < self.min_window:
            return None
        p99 = frame["leader_p99_ms"]
        if p99 <= 0:
            return None
        self._recent_p99.append(p99)
        peak = max(self._recent_p99)
        inputs = {"leader_p99_ms": round(p99, 2),
                  "peak_p99_ms": round(peak, 2),
                  "leader_n": frame["leader_n"],
                  "sheds": frame["sheds"],
                  "depth": frame["depth"], "slo_ms": self.slo_ms}
        ratio = self.slo_ms / peak
        if peak > self.slo_ms:
            # over SLO (in ANY recent window): shrink the tolerated
            # queue (ratio < 1, floored so one horrible window cannot
            # collapse the watermark)
            return current * max(ratio, 0.5), inputs
        if frame["sheds"] > 0 and peak < self.GROW_GUARD * self.slo_ms:
            # sheds while even the PEAK window comfortably met the
            # SLO: work was refused that the cluster could absorb
            return current * min(ratio, 2.0), inputs
        return None


class LingerController(KnobController):
    """Adaptive scatter-linger CEILING (``scatter_linger_max_ms``)
    from measured batch-formation gain vs added wait: batches forming
    unfilled while queries queue -> a longer linger buys fill (one RPC
    per worker serves more queries); batches already ~full -> the
    linger never actually waits (the saturation skip) and a narrower
    ceiling bounds the worst-case added latency. The linger FLOOR
    stays static — a lone query's latency tax is not this
    controller's to spend."""

    NARROW_FILL = 0.9
    WIDEN_FILL = 0.6
    TARGET_FILL = 0.75

    def __init__(self, cfg, read, write) -> None:
        # the floor can never drop the CEILING to (or below) the
        # static linger minimum: hi <= lo would flip the coalescer
        # into fixed-linger mode while this controller kept reporting
        # a steered ceiling — keep a real adaptive range above lo
        floor = max(cfg.autopilot_linger_floor_ms,
                    cfg.scatter_linger_min_ms * 1.5)
        super().__init__("scatter_linger_max_ms", floor,
                         max(cfg.autopilot_linger_ceiling_ms, floor),
                         read, write, cfg.scatter_linger_max_ms)
        self.min_window = cfg.autopilot_min_window

    def sense(self, frame, current):
        batches, items = frame["batches"], frame["items"]
        if batches < 4 or items < self.min_window:
            return None
        fill = items / (batches * max(frame["max_batch"], 1))
        inputs = {"fill": round(fill, 3), "batches": int(batches),
                  "items": int(items), "depth": frame["depth"]}
        if fill >= self.NARROW_FILL:
            return current * self.NARROW_FILL * (
                self.TARGET_FILL / fill), inputs
        if fill < self.WIDEN_FILL and frame["depth"] > 0:
            return current * min(self.TARGET_FILL / max(fill, 0.05),
                                 2.0), inputs
        return None


class SlowTripController(KnobController):
    """``breaker_slow_threshold_ms`` from the cross-worker latency-EWMA
    spread: the gray-failure trip should mean "this worker is an
    outlier against its peers right now", so the threshold tracks
    median(per-worker EWMA) x a spread multiple. Needs at least two
    workers with enough successful samples — one worker has no peers
    to be an outlier against."""

    def __init__(self, cfg, read, write) -> None:
        super().__init__("breaker_slow_threshold_ms",
                         cfg.autopilot_slow_floor_ms,
                         cfg.autopilot_slow_ceiling_ms,
                         read, write, cfg.breaker_slow_threshold_ms)
        self.mult = cfg.autopilot_slow_spread_mult
        self.min_samples = max(1, cfg.breaker_slow_min_samples)

    def sense(self, frame, current):
        ewmas = [e * 1e3 for e, n in frame["worker_ewmas"].values()
                 if n >= self.min_samples]
        if len(ewmas) < 2:
            return None
        med = statistics.median(ewmas)
        return med * self.mult, {
            "median_ewma_ms": round(med, 2),
            "workers": len(ewmas), "spread_mult": self.mult}


class TierBudgetController(KnobController):
    """``tier_hot_budget_mb`` (ISSUE 18) steered toward the configured
    tier hit-rate target: a window whose hot-tier hit rate falls short
    of ``tier_hit_target`` grows the HBM budget proportionally to the
    shortfall (more segments stay resident, fewer searches stall on the
    upload ring); a window comfortably over it shrinks the budget and
    returns HBM to whatever else wants it (the dense carve-out, larger
    query batches). Skipped segments count as neither hit nor fault —
    a skip costs nothing, so it must not dilute the pressure signal.
    Needs real tier traffic: a window with too few lookups (everything
    skipped, or no queries) carries no signal."""

    def __init__(self, cfg, read, write) -> None:
        super().__init__("tier_hot_budget_mb",
                         cfg.autopilot_tier_floor_mb,
                         cfg.autopilot_tier_ceiling_mb,
                         read, write, cfg.tier_hot_budget_mb,
                         integral=True)
        self.target = min(max(cfg.tier_hit_target, 0.0), 1.0)
        self.min_window = cfg.autopilot_min_window

    def sense(self, frame, current):
        lookups = frame["tier_hits"] + frame["tier_faults"]
        if lookups < self.min_window:
            return None
        rate = frame["tier_hits"] / lookups
        inputs = {"tier_hit_rate": round(rate, 3),
                  "tier_lookups": int(lookups),
                  "hit_target": self.target}
        return current * (1.0 + (self.target - rate)), inputs


class Autopilot:
    """The leader-side control loop. Constructed on every node (like
    the rebalancer); ``maybe_run`` is called from the reconcile sweep
    loop and does work only while this node is leader and the loop is
    enabled, self-paced by ``autopilot_interval_ms``.

    Thread model: ``run_once`` executes only on the sweep thread (or a
    test's thread) — controller state needs no lock. The decision ring
    is a bounded deque (GIL-atomic appends; readers copy). Knob writes
    are plain attribute stores on the live objects (admission
    controller, coalescer, resilience bundle) — the same GIL-atomic
    contract their hot-path readers already rely on. ``set_enabled``
    (the kill switch) takes a small lock only against a concurrent
    sweep deciding from pre-revert reads."""

    def __init__(self, node: SearchNode) -> None:
        self.node = node
        cfg = node.config
        self.cfg = cfg
        self.enabled = bool(cfg.autopilot_enabled)
        self.interval_s = cfg.autopilot_interval_ms / 1e3
        self.hysteresis = max(0.0, cfg.autopilot_hysteresis)
        self.step = min(max(cfg.autopilot_step, 0.05), 1.0)
        self.confirm = max(1, cfg.autopilot_confirm)
        self._ring: deque[dict] = deque(maxlen=max(16,
                                                   cfg.autopilot_ring))
        self._seq = 0
        self._last_decision_mono = 0.0
        self._last_run = time.monotonic()
        self._lock = threading.Lock()   # kill switch vs in-flight sweep

        self.controllers: list[KnobController] = [
            HedgeController(
                cfg,
                read=lambda: float(node.hedge_ms),
                write=lambda v: setattr(node, "hedge_ms", float(v))),
            SlowTripController(
                cfg,
                read=lambda: node.resilience.slow_threshold_s * 1e3,
                write=lambda v: setattr(node.resilience,
                                        "slow_threshold_s", v / 1e3)),
        ]
        # the watermark controller only exists where backpressure is
        # armed: with the high-water mark statically 0 the operator
        # turned queue shedding off, and a multiplicative controller
        # has no lever to scale (0 x anything = 0)
        if cfg.admission_enabled and cfg.admission_queue_high_water > 0:
            self.controllers.append(WatermarkController(
                cfg,
                read=lambda: float(node.admission.high_water),
                write=self._write_watermarks,
                revert=self._revert_watermarks))
        # the linger controller only exists where there is an adaptive
        # scatter coalescer to steer (micro-batching on, adaptation
        # armed — hi > lo; with bounds disabled the operator chose a
        # fixed linger, which is theirs to keep)
        b = node.scatter_batcher
        if b is not None:
            lo_s, hi_s = b.linger_bounds()
            if hi_s > lo_s:
                self.controllers.append(LingerController(
                    cfg,
                    read=lambda: b.linger_bounds()[1] * 1e3,
                    write=lambda v: b.set_linger_bounds(hi_s=v / 1e3)))
        # the tier-budget controller only exists where a tiered
        # segmented index is serving (engine.tier) — it steers this
        # node's hot-set HBM budget toward the tier hit-rate target.
        # Not every autopilot host HAS an engine: the stateless router
        # tier runs an autopilot too (hedge/linger knobs) and serves
        # no index at all
        tier = getattr(getattr(node, "engine", None), "tier", None)
        if tier is not None:
            self.controllers.append(TierBudgetController(
                cfg,
                read=lambda: float(tier.budget_bytes >> 20),
                write=lambda v: tier.set_budget(int(v) << 20)))
        # the critical/high ratio the watermark controller preserves
        hw = max(1, cfg.admission_queue_high_water)
        self._critical_ratio = (cfg.admission_queue_critical / hw
                                if cfg.admission_queue_critical > 0
                                else 0.0)

        # sensor windows (shared across controllers; advanced once per
        # sweep so every controller sees the same frame)
        self._w_scatter = HistWindow("scatter_rpc")
        self._w_leader = HistWindow("leader_search")
        self._c_batches = CounterWindow("scatter_batches")
        self._c_items = CounterWindow("scatter_items")
        self._c_sheds = CounterWindow("admission_shed_total")
        self._c_tier_hits = CounterWindow("tier_hot_hits")
        self._c_tier_faults = CounterWindow("tier_cold_faults")

        # windows start NOW: the first control pass must see only what
        # happened since this autopilot existed, not the process's
        # whole metric history (an in-process test cluster shares
        # global_metrics across nodes)
        self._reset_windows()
        if self.enabled:
            self._bootstrap()
        self._publish_gauges()

    # ---- knob write helpers ----

    def _write_watermarks(self, v: float) -> None:
        adm = self.node.admission
        adm.high_water = int(v)
        if self._critical_ratio > 0:
            adm.critical = max(round(v * self._critical_ratio),
                               adm.high_water + 1)

    def _revert_watermarks(self) -> None:
        """Kill-switch path: BOTH watermarks restored verbatim from
        config — re-deriving critical through the float ratio could be
        off by one (int truncation of c/h*h), and the revert contract
        is exact static values, not a reconstruction."""
        adm = self.node.admission
        adm.high_water = self.cfg.admission_queue_high_water
        adm.critical = self.cfg.admission_queue_critical

    def _bootstrap(self) -> None:
        """Arm sensors that static config leaves off: with
        ``breaker_slow_threshold_ms=0`` the per-worker latency EWMA is
        never collected, so the slow-trip controller would starve
        forever. Seed the threshold at its ceiling — collection turns
        on, no trip can fire before the controller has derived a real
        value from the spread."""
        res = self.node.resilience
        if res.slow_threshold_s <= 0:
            ceiling_ms = self.cfg.autopilot_slow_ceiling_ms
            res.slow_threshold_s = ceiling_ms / 1e3
            self._record(knob="breaker_slow_threshold_ms",
                         current=0.0, target=ceiling_ms,
                         new=ceiling_ms, direction=1, applied=True,
                         reason="bootstrap:arm_ewma_collection",
                         inputs={})

    # ---- the control loop ----

    def maybe_run(self) -> None:
        """Self-paced pass inside the leader's sweep loop (mirrors
        ``Rebalancer.maybe_run``)."""
        if not self.enabled or self.cfg.autopilot_interval_ms < 0:
            return
        now = time.monotonic()
        if now - self._last_run < self.interval_s:
            return
        self._last_run = now
        self.run_once()

    def _frame(self) -> dict:
        sc_counts, sc_n = self._w_scatter.advance()
        ld_counts, ld_n = self._w_leader.advance()
        sp95 = delta_quantile(sc_counts, 0.95)
        lp99 = delta_quantile(ld_counts, 0.99)
        b = self.node.scatter_batcher
        depth = global_metrics.get("last_scatter_queue_depth", 0.0)
        if b is not None:
            depth = max(depth, float(b.backlog()))
        return {
            "scatter_p95_ms": (sp95 or 0.0) * 1e3, "scatter_n": sc_n,
            "leader_p99_ms": (lp99 or 0.0) * 1e3, "leader_n": ld_n,
            "batches": self._c_batches.advance(),
            "items": self._c_items.advance(),
            "sheds": self._c_sheds.advance(),
            "tier_hits": self._c_tier_hits.advance(),
            "tier_faults": self._c_tier_faults.advance(),
            "depth": depth,
            "max_batch": b.max_batch if b is not None else 0,
            "worker_ewmas": self.node.resilience.latency_snapshot(),
        }

    def run_once(self) -> list[dict]:
        """One control pass: advance the sensor windows, decide every
        knob, apply confirmed moves (inside an ``autopilot.sweep``
        span when any knob changed), record every decision. Public so
        tests and operators can force a pass. Returns the applied
        decisions."""
        if not self.enabled:
            return []
        # the fault point AND the sensor reads run OUTSIDE the lock:
        # an armed delay rule sleeps, and the frame takes the metrics/
        # EWMA locks — the kill switch must never queue behind either
        # (run_once itself is single-threaded: the sweep thread). A
        # kill switch racing this frame is re-checked under the lock
        # before anything is decided or written.
        global_injector.check("leader.autopilot")
        frame = self._frame()
        with self._lock:
            if not self.enabled:
                return []
            global_metrics.inc("autopilot_sweeps")
            decisions = [self._decide(c, frame)
                         for c in self.controllers]
            applied = [d for d in decisions if d is not None
                       and d["applied"]]
            if applied:
                # the sweep that changes a knob gets a trace of its
                # own: one span, one knob_adjusted event per change,
                # carrying the sensor inputs that justified it
                with global_tracer.span(
                        "autopilot.sweep",
                        attrs={"adjusted": len(applied)}) as sp:
                    for d in applied:
                        ctl = next(c for c in self.controllers
                                   if c.knob == d["knob"])
                        ctl.write(d["new"])
                        ctl.last_dir = d["direction"]
                        ctl.last_adjust_mono = time.monotonic()
                        ctl.adjustments += 1
                        global_metrics.inc("autopilot_adjustments")
                        sp.event("knob_adjusted", knob=d["knob"],
                                 old=d["current"], new=d["new"],
                                 direction=d["direction"],
                                 **d["inputs"])
                        log.info("autopilot adjusted knob",
                                 knob=d["knob"], old=d["current"],
                                 new=d["new"],
                                 direction=d["direction"],
                                 **d["inputs"])
            self._publish_gauges()
            return applied

    # EWMA weight of the NEW raw target in the smoothed target: the
    # band/step act on the filtered value, so a single outlier window
    # moves the effective target only halfway toward itself
    TARGET_SMOOTHING = 0.5

    def _decide(self, ctl: KnobController, frame: dict) -> dict | None:
        """The shared discipline: clamp -> target smoothing ->
        hysteresis dead band -> raw-agreement + reversal guard ->
        direction confirmation -> damped step. Returns the decision
        record (also appended to the ring), or None when the window
        carried no signal for this knob (not recorded — a ring full
        of idle-cluster no-ops would bury the decisions that
        matter)."""
        current = ctl.read()
        sensed = ctl.sense(frame, current)
        if sensed is None:
            # a no-signal sweep breaks any confirmation streak: the
            # "consecutive sweeps" contract means consecutive — one
            # stale proposal from before a traffic gap must not let a
            # single noisy window move the knob hours later
            ctl.reset()
            return None
        raw, inputs = sensed
        raw = min(max(raw, ctl.floor), ctl.ceiling)
        # target smoothing: the band and the step see an EWMA of the
        # sensed targets, not each window's raw sample (a convex
        # combination of clamped values stays clamped)
        target = (raw if ctl.smoothed is None else
                  self.TARGET_SMOOTHING * raw
                  + (1.0 - self.TARGET_SMOOTHING) * ctl.smoothed)
        ctl.smoothed = target
        band = self.hysteresis * max(abs(current), 1e-9)
        err = target - current
        if abs(err) <= band:
            ctl.reset()
            return self._record(
                knob=ctl.knob, current=current, target=target,
                new=None, direction=0, applied=False,
                reason="hold:in_band", inputs=inputs)
        direction = 1 if err > 0 else -1
        # raw agreement: this sweep's UNSMOOTHED sample must point the
        # same way (beyond the band) before it may confirm — a sensor
        # alternating hard around the knob never accumulates
        # confirmations, however far its smoothed mean drifts
        raw_dir = (1 if raw > current + band
                   else -1 if raw < current - band else 0)
        if raw_dir != direction:
            ctl.reset()
            return self._record(
                knob=ctl.knob, current=current, target=target,
                new=None, direction=direction, applied=False,
                reason="hold:noisy", inputs=inputs)
        # reversal guard: undoing the LAST applied adjustment demands
        # an error beyond TWICE the band — noise that barely clears
        # the band cannot walk the knob back and forth, while a
        # genuine load step (error >> band) reverses immediately
        if (ctl.last_dir != 0 and direction != ctl.last_dir
                and abs(err) <= 2.0 * band):
            ctl.pending_dir = 0
            ctl.confirms = 0
            return self._record(
                knob=ctl.knob, current=current, target=target,
                new=None, direction=direction, applied=False,
                reason="hold:reversal_guard", inputs=inputs)
        if direction != ctl.pending_dir:
            ctl.pending_dir = direction
            ctl.confirms = 1
        else:
            ctl.confirms += 1
        if ctl.confirms < self.confirm:
            return self._record(
                knob=ctl.knob, current=current, target=target,
                new=None, direction=direction, applied=False,
                reason=f"hold:confirm_{ctl.confirms}"
                       f"_of_{self.confirm}", inputs=inputs)
        new = ctl.quantize(min(max(current + self.step * err,
                                   ctl.floor), ctl.ceiling))
        if new == ctl.quantize(current) and ctl.integral:
            # minimum-step rule for integer knobs: at small values the
            # damped fraction rounds back onto the current value and
            # the controller deadlocks (a watermark of 4 with a 0.83
            # shrink ratio proposes 3.67 -> rounds to 4, forever) —
            # an out-of-band error always moves an integral knob by
            # at least one unit toward the target
            new = ctl.quantize(min(max(current + direction,
                                       ctl.floor), ctl.ceiling))
        if new == ctl.quantize(current):
            return self._record(
                knob=ctl.knob, current=current, target=target,
                new=None, direction=direction, applied=False,
                reason="hold:quantized", inputs=inputs)
        return self._record(
            knob=ctl.knob, current=current, target=target, new=new,
            direction=direction, applied=True, reason="adjusted",
            inputs=inputs)

    # ---- kill switch ----

    def set_enabled(self, on: bool) -> dict:
        """The global kill switch. Disabling reverts EVERY managed
        knob to its static config value before returning — by the
        time the caller sees the reply, the cluster behaves exactly
        as if the autopilot had never run. Re-enabling restarts from
        static values with fresh sensor windows (no stale trend may
        carry over)."""
        with self._lock:
            if on == self.enabled:
                return self.snapshot()
            self.enabled = on
            if on:
                self._reset_windows()
                for ctl in self.controllers:
                    ctl.reset()
                    ctl.clear_sensor_state()
                self._bootstrap()
                self._last_run = time.monotonic()
                log.info("autopilot enabled")
            else:
                for ctl in self.controllers:
                    current = ctl.read()
                    ctl.revert()
                    ctl.reset()
                    ctl.last_dir = 0
                    self._record(
                        knob=ctl.knob, current=current,
                        target=ctl.static, new=ctl.static,
                        direction=0, applied=True,
                        reason="revert:kill_switch", inputs={})
                global_metrics.inc("autopilot_reverts")
                log.info("autopilot disabled; all knobs reverted to "
                         "static config")
            self._publish_gauges()
            return self.snapshot()

    def _reset_windows(self) -> None:
        for w in (self._w_scatter, self._w_leader):
            w.advance()
        for c in (self._c_batches, self._c_items, self._c_sheds,
                  self._c_tier_hits, self._c_tier_faults):
            c.advance()

    # ---- audit trail ----

    def _record(self, **kw) -> dict:
        self._seq += 1
        rec = {"seq": self._seq, "ts": round(epoch_now(), 3), **kw}
        self._ring.append(rec)
        self._last_decision_mono = time.monotonic()
        return rec

    def decisions(self, n: int = 50) -> list[dict]:
        """The newest ``n`` decision records, oldest first."""
        if n <= 0:
            return []
        recs = list(self._ring)
        return recs[-n:]

    def snapshot(self) -> dict:
        """Operator view for ``GET /api/autopilot``, ``/api/health``
        consumers, and the CLI summary blocks."""
        now = time.monotonic()
        knobs = {}
        for ctl in self.controllers:
            knobs[ctl.knob] = {
                "current": round(ctl.read(), 2),
                "static": round(ctl.static, 2),
                "floor": ctl.floor, "ceiling": ctl.ceiling,
                "last_direction": ctl.last_dir,
                "adjustments": ctl.adjustments,
                "last_adjust_age_s":
                    round(now - ctl.last_adjust_mono, 1)
                    if ctl.last_adjust_mono else None,
            }
        return {"enabled": self.enabled,
                "interval_ms": self.cfg.autopilot_interval_ms,
                "hysteresis": self.hysteresis, "step": self.step,
                "confirm": self.confirm,
                "p99_slo_ms": self.cfg.autopilot_p99_slo_ms,
                "knobs": knobs,
                "decisions_recorded": len(self._ring),
                "last_decision_age_s":
                    round(now - self._last_decision_mono, 1)
                    if self._last_decision_mono else None}

    def _publish_gauges(self) -> None:
        global_metrics.set_gauge("autopilot_active",
                                 1.0 if self.enabled else 0.0)
        for ctl in self.controllers:
            k = ctl.knob
            global_metrics.set_gauge(f"autopilot_{k}", ctl.read())
            global_metrics.set_gauge(f"autopilot_{k}_floor", ctl.floor)
            global_metrics.set_gauge(f"autopilot_{k}_ceiling",
                                     ctl.ceiling)
            global_metrics.set_gauge(f"autopilot_{k}_direction",
                                     ctl.last_dir)

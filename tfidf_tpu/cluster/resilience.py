"""Cluster resilience primitives: retry policy + per-worker circuit breakers.

The reference's only failure machinery is the ZooKeeper session timeout
(the failure detector) plus swallow-and-continue scatter tolerance
(``Leader.java:67-69``). That detects *death* but not *degradation*: a
slow or flapping worker is retried at full cost on every RPC forever, and
a transient blip fails a request that one cheap retry would have saved.
This module adds the two missing disciplines, used by every leader→worker
RPC path in :mod:`tfidf_tpu.cluster.node` and the coordination client's
heartbeat/long-poll loops in :mod:`tfidf_tpu.cluster.coordination`:

- :class:`RetryPolicy` — bounded attempts, exponential backoff with
  jitter, an overall deadline, and a retryable-error classifier so only
  *transient* failures are retried (connection resets, 5xx) while
  application rejections (4xx) and timeouts propagate immediately.
- :class:`CircuitBreaker` / :class:`BreakerBoard` — per-worker
  closed → open → half-open breakers: after N consecutive failures the
  leader stops paying the connect/timeout cost for a sick worker and
  fast-fails (degraded, counted honestly) until a half-open probe
  succeeds.

Fault points (``tfidf_tpu.utils.faults``) cover every decision site —
``resilience.backoff`` before each retry sleep, ``resilience.breaker_trip``
when a breaker opens, ``resilience.breaker_probe`` when a half-open probe
is admitted — so the chaos suite can count and bound them.
"""

from __future__ import annotations

import http.client
import random
import socket
import threading
import time
import urllib.error
from concurrent.futures import wait as _futures_wait
from typing import Callable

from tfidf_tpu.utils.faults import FaultInjected, global_injector
from tfidf_tpu.utils.logging import get_logger
from tfidf_tpu.utils.metrics import global_metrics
from tfidf_tpu.utils.tracing import span_event

log = get_logger("cluster.resilience")


class RpcStatusError(RuntimeError):
    """A worker answered with a non-2xx status. Carrying the status as
    data (instead of string-matching ``repr``) lets the retry classifier
    distinguish gateway-transient statuses (retryable) from application
    rejections and deterministic server failures (not).

    ``deadline_exceeded`` marks a 504 that is a DEADLINE refusal — the
    worker (or the leader's own pre-dispatch check) declining to start
    work whose caller budget is already spent. Unlike a gateway 504 it
    is never retried (the budget cannot come back) and never indicts
    the worker (refusing honestly is healthy behavior).

    ``retry_after_s`` carries a 429 shed reply's ``Retry-After`` header
    (the admission layer's honest back-off hint): the retry policy
    never re-attempts BEFORE it has elapsed — see
    :func:`retry_after_of`.

    ``fenced`` marks the distinct leadership-fence rejection (403 +
    ``X-Fence-Rejected: 1``, cluster/fencing.py): the caller's leader
    epoch is STALE — a newer leader exists. Never retried (the epoch
    cannot grow back) and never a worker fault (refusing a deposed
    leader is the worker doing its job); the leader's correct reaction
    is to step down (``SearchNode._fence_step_down``).

    ``proto`` marks the distinct wire-protocol rejection (426 +
    ``X-Proto-Rejected: 1``, cluster/protover.py): the caller's
    declared wire version is below the handler's compat floor. Never
    retried (a binary's version cannot grow back mid-flight) and never
    a worker fault (refusing an out-of-window peer during a rolling
    upgrade is the handler doing its job — a breaker that opened on it
    would amplify a routine upgrade into an outage)."""

    def __init__(self, url: str, status: int,
                 deadline_exceeded: bool = False,
                 retry_after_s: float | None = None,
                 fenced: bool = False,
                 proto: bool = False,
                 compute_fault: str | None = None,
                 poison_fps: tuple[str, ...] = ()) -> None:
        super().__init__(f"{url} -> {status}"
                         + (" (deadline exceeded)" if deadline_exceeded
                            else "")
                         + (" (fenced: stale leader epoch)" if fenced
                            else "")
                         + (" (proto: version outside compat window)"
                            if proto else "")
                         + (f" (compute fault: {compute_fault})"
                            if compute_fault else ""))
        self.url = url
        self.status = status
        self.deadline_exceeded = deadline_exceeded
        self.retry_after_s = retry_after_s
        self.fenced = fenced
        self.proto = proto
        # ``X-Compute-Fault`` reply header: the worker's DEVICE failed
        # (oom/compile/transient/poison taxonomy below), not its
        # process or the network. Never retried (the same batch would
        # hit the same device state — the retry storm the taxonomy
        # exists to prevent); a poison fault additionally never indicts
        # the worker (the QUERY is at fault, and the leader's
        # quarantine — not the breaker — is the right response).
        self.compute_fault = compute_fault
        # ``X-Poison-Fingerprints``: per-query blame for a poison fault
        # (cluster/quarantine.py fingerprints), so a coalesced batch's
        # innocent cohort is never quarantined alongside the poison
        # query.
        self.poison_fps = tuple(poison_fps)


class CircuitOpenError(RuntimeError):
    """Fast-fail: the target worker's breaker is open (or its single
    half-open probe slot is taken). No RPC was attempted."""


class DeadlineExpired(RuntimeError):
    """The caller's budget ran out BEFORE dispatch — no RPC was made.
    Never retried, and (unlike a worker's 504 deadline refusal, which
    proves the worker alive) it carries NO evidence about the target:
    ``worker_call`` releases the breaker without recording success or
    failure."""


# connection-level failures: the peer is unreachable or the socket died.
_CONNECTION_ERRORS = (
    ConnectionError,            # covers reset/refused/aborted/broken pipe
    http.client.BadStatusLine,
    http.client.CannotSendRequest,
    http.client.NotConnected,
    http.client.RemoteDisconnected,
)


# statuses that signal TRANSIENT unavailability (gateway/overload) worth
# a retry. A plain 500 is a deterministic server-side failure — e.g. a
# worker engine crash on this very batch (/worker/process-batch's honest
# failure reply) — and re-running it would multiply the sick worker's
# engine load rpc_max_attempts-fold per scatter; fail fast and count it.
_TRANSIENT_STATUSES = frozenset({502, 503, 504})

# 429 is the admission layer's EXPLICIT shed (cluster/admission.py):
# transient by definition, but retrying before its Retry-After hint has
# elapsed is exactly the hammering the shed exists to stop. The retry
# policy enforces that: see retry_after_of / RetryPolicy.call.
_SHED_STATUS = 429


def retry_after_of(e: BaseException) -> float | None:
    """The shed reply's ``Retry-After`` hint in seconds, or None when
    ``e`` is not a 429 (or carries no parseable hint — the HTTP-date
    form is treated as absent rather than guessed at). The retry policy
    uses it as a FLOOR on the back-off delay: a shed response is never
    re-attempted before the admitting side said a token would exist."""
    if isinstance(e, RpcStatusError) and e.status == _SHED_STATUS:
        return e.retry_after_s if e.retry_after_s is not None else 0.0
    if isinstance(e, urllib.error.HTTPError) and e.code == _SHED_STATUS:
        try:
            return float(e.headers.get("Retry-After", ""))
        except (TypeError, ValueError):
            return 0.0
    return None


# the leadership-fence status (cluster/fencing.py): a worker refusing
# a STALE leader epoch. Distinct from any other 4xx in consequence —
# the leader must step down, not merely fail the request.
_FENCE_STATUS = 403

# the wire-protocol rejection status (cluster/protover.py
# PROTO_STATUS): a handler refusing a peer whose declared wire version
# is below its compat floor. 4xx on purpose — already non-retryable and
# never a worker fault under the classifiers below; the explicit
# ``proto`` flag and :func:`is_proto_rejection` make the distinct
# consequence (surface version skew to the operator, never trip a
# breaker) testable and graftcheck-checkable.
_PROTO_STATUS = 426

# disk full (utils/storage.py STORAGE_FULL_STATUS): an upload or
# checkpoint hit ENOSPC. Deliberately NON-retryable (a full disk does
# not drain on retry timescales; hammering it multiplies write load
# exactly when the disk needs relief) and NEVER a worker fault — the
# node still serves reads perfectly, so a breaker that opened on 507s
# would mark a healthy-for-reads node dead and shrink the very capacity
# the full disk is starving.
_STORAGE_FULL_STATUS = 507


def is_fence_rejection(e: BaseException) -> bool:
    """A worker's leadership-fence rejection (403 +
    ``X-Fence-Rejected: 1``): the calling leader's epoch is stale.
    NEVER retryable (a deposed epoch cannot become current again) and
    NEVER a worker fault (the worker is healthy and doing exactly its
    job); the leader reacts by stepping down."""
    if isinstance(e, RpcStatusError):
        return e.fenced
    if isinstance(e, urllib.error.HTTPError) and e.code == _FENCE_STATUS:
        try:
            return e.headers.get("X-Fence-Rejected") == "1"
        except Exception:
            return False
    return False


def is_proto_rejection(e: BaseException) -> bool:
    """A handler's wire-protocol rejection (426 +
    ``X-Proto-Rejected: 1``): the calling peer's declared wire version
    is below the handler's compat floor (cluster/protover.py). NEVER
    retryable (the binary's version cannot change mid-flight) and NEVER
    a worker fault (the handler is healthy and enforcing the window —
    during a rolling upgrade this is routine, not an outage); callers
    surface it as version skew instead of masking it as a failure."""
    if isinstance(e, RpcStatusError):
        return e.proto
    if isinstance(e, urllib.error.HTTPError) and e.code == _PROTO_STATUS:
        try:
            return e.headers.get("X-Proto-Rejected") == "1"
        except Exception:
            return False
    return False


# message fragments that identify a device fault class when the
# exception TYPE alone cannot (XlaRuntimeError and friends are raised
# by jaxlib with the class buried in the message) — checked in order,
# first hit wins. The structured replacement for the string-match
# compile-retry gate this file's classifier superseded
# (cluster/node.py's old `"remote_compile" in repr(e)`).
_COMPUTE_OOM_MARKS = ("resource_exhausted", "out of memory", "oom")
_COMPUTE_COMPILE_MARKS = ("remote_compile", "tpu_compile_helper",
                          "compilation failure", "compile failed",
                          "compilation failed", "xla compilation")


def classify_compute_fault(e: BaseException) -> str | None:
    """The compute-fault taxonomy: ``"oom"`` / ``"compile"`` /
    ``"transient"`` / ``"poison"``, or None for anything that is not a
    device fault.

    Classification is exception-type first (the device nemesis and the
    fetch-seam poison detector raise typed exceptions), message
    taxonomy second (real jaxlib ``XlaRuntimeError``s carry the class
    in the message), and is shared by every consumer — the worker's
    compile-retry gate, the engine's ComputeHealth state machine, and
    the leader's poison quarantine — so the three can never drift on
    what counts as which fault. An ``RpcStatusError`` carrying a
    worker's ``X-Compute-Fault`` stamp classifies as that stamp (the
    worker already ran this function next to the device)."""
    stamped = getattr(e, "compute_fault", None)
    if stamped is not None:
        return stamped
    from tfidf_tpu.utils.device_nemesis import (DeviceCompileError,
                                                DeviceFault,
                                                DeviceOOMError,
                                                DevicePoisonedOutput,
                                                DeviceSickError,
                                                DeviceTransientError)
    if isinstance(e, DevicePoisonedOutput):
        return "poison"
    if isinstance(e, DeviceOOMError):
        return "oom"
    if isinstance(e, DeviceCompileError):
        return "compile"
    if isinstance(e, (DeviceTransientError, DeviceSickError)):
        return "transient"
    if isinstance(e, DeviceFault):
        return "transient"
    # real jax/jaxlib runtime errors: match on type name (jaxlib's
    # exception classes move between modules across versions — and the
    # CPU-only test image may not expose them at a stable import path)
    tname = type(e).__name__
    if tname in ("XlaRuntimeError", "JaxRuntimeError", "InternalError",
                 "ResourceExhaustedError"):
        msg = str(e).lower()
        if any(m in msg for m in _COMPUTE_OOM_MARKS):
            return "oom"
        if any(m in msg for m in _COMPUTE_COMPILE_MARKS):
            return "compile"
        return "transient"
    # the TPU tunnel surfaces remote-compile/OOM failures as PLAIN
    # RuntimeError: classify by the marks alone, and never default a
    # generic RuntimeError to "transient" — an arbitrary RuntimeError
    # is not a device fault
    if isinstance(e, RuntimeError):
        msg = str(e).lower()
        if any(m in msg for m in _COMPUTE_OOM_MARKS):
            return "oom"
        if any(m in msg for m in _COMPUTE_COMPILE_MARKS):
            return "compile"
    return None


def is_retryable(e: BaseException) -> bool:
    """Default retry classifier: transient transport failures,
    gateway-transient statuses (502/503/504), and 429 admission sheds
    (retried only AFTER their ``Retry-After`` hint — the policy floors
    the back-off delay at it, so internal clients and the CLI honor the
    shed signal instead of hammering a saturated leader). NOT retryable:
    other application-level 4xx (the request itself is wrong — retrying
    cannot fix it), deterministic 500s (see ``_TRANSIENT_STATUSES``),
    and timeouts (the worker may still be processing; a retry would
    double the caller's latency budget, the same reasoning as
    ``_ScatterClient``'s single stale-connection retry).
    ``FaultInjected`` counts as transient so armed chaos faults exercise
    the retry path."""
    if isinstance(e, socket.timeout):   # subclass of OSError — check first
        return False
    if isinstance(e, DeadlineExpired):
        return False   # the budget cannot come back
    if is_fence_rejection(e):
        return False   # a stale epoch cannot become current again
    if is_proto_rejection(e):
        return False   # the binary's wire version cannot change mid-flight
    if isinstance(e, FaultInjected):
        return True
    if isinstance(e, RpcStatusError):
        if e.deadline_exceeded:
            return False   # the caller's budget is spent; honest failure
        if e.compute_fault is not None:
            # a device fault is deterministic on the worker's current
            # device state: re-sending the same batch would multiply
            # the sick device's load attempt-fold (the retry storm).
            # Per-request FAILOVER to a replica — not retry to the same
            # worker — is the recovery path.
            return False
        return e.status in _TRANSIENT_STATUSES or e.status == _SHED_STATUS
    if isinstance(e, urllib.error.HTTPError):
        return e.code in _TRANSIENT_STATUSES or e.code == _SHED_STATUS
    if isinstance(e, urllib.error.URLError):
        return isinstance(e.reason, _CONNECTION_ERRORS + (OSError,)) \
            and not isinstance(e.reason, socket.timeout)
    return isinstance(e, _CONNECTION_ERRORS)


def is_worker_fault(e: BaseException) -> bool:
    """Breaker accounting classifier: does this failure indict the WORKER
    (count toward opening its breaker)? An application rejection (4xx,
    e.g. 415 on a binary upload) comes from a healthy worker and must not
    trip its breaker; everything else — connection failures, timeouts,
    5xx — does. A 429 shed falls under the 4xx rule BY DESIGN: shedding
    is healthy overload behavior (cluster/admission.py), and a breaker
    that opened on sheds would amplify the very overload the shed is
    relieving (fast-fails would mark a live node dead). A leadership-
    fence 403 likewise: the WORKER is healthy — it is the calling
    leader that is deposed (cluster/fencing.py). And a wire-protocol
    426 likewise: the handler is healthy — it is the CALLER that is
    out of the compat window (cluster/protover.py); breakers opening
    on routine rolling-upgrade skew would turn an upgrade into an
    outage."""
    if is_fence_rejection(e):
        return False
    if is_proto_rejection(e):
        return False
    if isinstance(e, RpcStatusError):
        if e.deadline_exceeded:
            return False   # honest refusal from a healthy worker
        if e.compute_fault == "poison":
            # the QUERY is at fault, not the worker: a poison query
            # serially tripping every replica's breaker is exactly the
            # cascade the quarantine exists to stop — the worker stays
            # in rotation and the leader quarantines the fingerprint
            return False
        return e.status >= 500 and e.status != _STORAGE_FULL_STATUS
    if isinstance(e, urllib.error.HTTPError):
        return e.code >= 500 and e.code != _STORAGE_FULL_STATUS
    return True


class RetryPolicy:
    """Bounded retry with exponential backoff + jitter and a deadline.

    ``call(fn)`` runs ``fn`` up to ``max_attempts`` times. An exception
    the classifier rejects propagates immediately; a retryable one sleeps
    ``base * 2**attempt`` (capped at ``max_delay_s``, ±``jitter``
    fraction) and tries again, unless attempts or the overall deadline
    (``deadline_s``; 0 disables) would be exceeded. ``sleep``/``clock``/
    ``rng`` are injectable for deterministic tests."""

    def __init__(self, max_attempts: int = 3, base_delay_s: float = 0.05,
                 max_delay_s: float = 2.0, jitter: float = 0.25,
                 deadline_s: float = 0.0,
                 classify: Callable[[BaseException], bool] = is_retryable,
                 name: str = "rpc", sleep=time.sleep,
                 clock=time.monotonic, rng: random.Random | None = None
                 ) -> None:
        self.max_attempts = max(1, max_attempts)
        self.base_delay_s = base_delay_s
        self.max_delay_s = max_delay_s
        self.jitter = jitter
        self.deadline_s = deadline_s
        self.classify = classify
        self.name = name
        self._sleep = sleep
        self._clock = clock
        self._rng = rng or random.Random()

    def backoff_delay(self, attempt: int) -> float:
        """Delay before retry number ``attempt`` (1-based)."""
        d = min(self.max_delay_s, self.base_delay_s * (2 ** (attempt - 1)))
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        return max(0.0, d)

    def call(self, fn, classify=None):
        classify = classify or self.classify
        t0 = self._clock()
        for attempt in range(1, self.max_attempts + 1):
            try:
                return fn()
            except Exception as e:
                if attempt >= self.max_attempts or not classify(e):
                    raise
                delay = self.backoff_delay(attempt)
                shed_wait = retry_after_of(e)
                if shed_wait is not None:
                    # non-retryable-before-Retry-After: the shed reply's
                    # hint FLOORS the delay — re-attempting sooner is
                    # the hammering the 429 exists to stop
                    delay = max(delay, shed_wait)
                    global_metrics.inc(f"{self.name}_shed_waits")
                if (self.deadline_s > 0
                        and self._clock() - t0 + delay > self.deadline_s):
                    raise   # the budget is spent; honest failure now
                global_metrics.inc(f"{self.name}_retries")
                # visible in the request trace: which attempt failed,
                # with what, and how long the backoff slept
                span_event("retry", attempt=attempt,
                           delay_ms=round(delay * 1e3, 1),
                           err=repr(e)[:120])
                global_injector.check("resilience.backoff")
                self._sleep(delay)
        raise AssertionError("unreachable")   # loop always returns/raises


def hedge_laggards(futures: dict, delay_s: float, on_laggard) -> set:
    """Hedged-read primitive ("The Tail at Scale", Dean & Barroso 2013):
    wait up to ``delay_s`` for the futures in ``futures`` (future ->
    tag); for each one still outstanding at the deadline invoke
    ``on_laggard(tag)`` exactly once and return the set of laggard tags.

    The primitive only DETECTS the laggards — the caller decides what a
    hedge is (the leader re-issues the laggard's ownership slice to the
    next replica) and owns merging/deduping the duplicate results.
    ``on_laggard`` runs on the calling thread and must dispatch async
    work rather than block; a raising callback is counted
    (``hedge_dispatch_failures``) and swallowed so one bad hedge cannot
    take down the primary gather it exists to protect."""
    if delay_s <= 0 or not futures:
        return set()
    _done, pending = _futures_wait(set(futures), timeout=delay_s)
    laggards = set()
    for fut in pending:
        tag = futures[fut]
        laggards.add(tag)
        try:
            on_laggard(tag)
        except Exception as e:
            global_metrics.inc("hedge_dispatch_failures")
            log.warning("hedge dispatch failed", target=str(tag),
                        err=repr(e))
    if laggards:
        global_metrics.inc("hedges_dispatched", len(laggards))
    return laggards


# breaker states
CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    """Per-target circuit breaker: closed → open after
    ``failure_threshold`` CONSECUTIVE failures → half-open probe after
    ``reset_s`` → closed on probe success, re-open on probe failure.

    ``acquire()`` admits or rejects a call (one probe at a time while
    half-open); the caller reports the outcome via ``record_success`` /
    ``record_failure``. The fault points at the trip and probe sites are
    observe-only: an armed ``raise`` there is swallowed (the fire counter
    still increments) because both run inside callers' error paths."""

    def __init__(self, failure_threshold: int = 5, reset_s: float = 5.0,
                 clock=time.monotonic, name: str = "") -> None:
        self.failure_threshold = max(1, failure_threshold)
        self.reset_s = reset_s
        self.name = name
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._open_until = 0.0
        self._probe_inflight = False
        self.transitions: list[str] = [CLOSED]   # audit trail for tests

    @property
    def state(self) -> str:
        with self._lock:
            if self._state == OPEN and self._clock() >= self._open_until:
                return HALF_OPEN   # would admit a probe
            return self._state

    def is_open(self) -> bool:
        """Non-consuming check: True while calls would be rejected
        outright (does NOT claim the half-open probe slot — use it for
        routing decisions, ``acquire`` for actual calls)."""
        with self._lock:
            if self._state == OPEN:
                return self._clock() < self._open_until
            if self._state == HALF_OPEN:
                return self._probe_inflight
            return False

    def acquire(self) -> None:
        """Admit a call or raise :class:`CircuitOpenError`."""
        with self._lock:
            if self._state == CLOSED:
                return
            if self._state == OPEN:
                if self._clock() < self._open_until:
                    raise CircuitOpenError(
                        f"breaker open for {self.name or 'target'}")
                self._transition(HALF_OPEN)
            # half-open: exactly one probe in flight
            if self._probe_inflight:
                raise CircuitOpenError(
                    f"breaker half-open probe in flight for "
                    f"{self.name or 'target'}")
            self._probe_inflight = True
        self._observe("resilience.breaker_probe")
        global_metrics.inc("breaker_probes")

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probe_inflight = False
            if self._state != CLOSED:
                self._transition(CLOSED)
                closed = True
            else:
                closed = False
        if closed:
            global_metrics.inc("breaker_closed")
            log.info("circuit breaker closed", target=self.name)

    def release(self) -> None:
        """Outcome unknown (no RPC was attempted, e.g. the caller's
        budget expired pre-dispatch): free the half-open probe slot
        without recording evidence either way — a breaker must never
        CLOSE on a worker that was not contacted."""
        with self._lock:
            self._probe_inflight = False

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            self._probe_inflight = False
            tripped = False
            if self._state == HALF_OPEN or (
                    self._state == CLOSED
                    and self._failures >= self.failure_threshold):
                self._transition(OPEN)
                self._open_until = self._clock() + self.reset_s
                tripped = True
            elif self._state == OPEN:
                # failure observed while open (e.g. a call admitted just
                # before the trip): push the reset window out
                self._open_until = self._clock() + self.reset_s
        if tripped:
            self._observe("resilience.breaker_trip")
            global_metrics.inc("breaker_opened")
            span_event("breaker_trip", target=self.name)
            log.warning("circuit breaker opened", target=self.name,
                        failures=self._failures)

    def trip_slow(self) -> None:
        """Gray-failure trip: force OPEN now. Called by the latency
        EWMA when a worker is slow-but-ALIVE — its calls succeed, so
        consecutive-failure counting never fires, yet every scatter it
        owns drags to the deadline. The normal half-open probe path
        re-admits it; the EWMA restarts from scratch on trip (the
        caller resets it) so one slow era cannot re-condemn a
        recovered worker forever."""
        with self._lock:
            self._probe_inflight = False
            self._failures = 0
            if self._state != OPEN:
                self._transition(OPEN)
            self._open_until = self._clock() + self.reset_s
        self._observe("resilience.breaker_trip")
        global_metrics.inc("breaker_opened")
        span_event("breaker_trip", target=self.name, gray=1)
        log.warning("circuit breaker opened (gray failure: latency)",
                    target=self.name)

    def _transition(self, state: str) -> None:
        self._state = state
        self.transitions.append(state)
        if len(self.transitions) > 64:   # bounded audit trail: a
            del self.transitions[:-64]   # flapping worker must not leak

    @staticmethod
    def _observe(point: str) -> None:
        try:
            global_injector.check(point)
        except FaultInjected:
            pass   # observe-only site; the fire counter already ticked


class BreakerBoard:
    """One :class:`CircuitBreaker` per worker URL, created on demand and
    pruned when workers leave the registry."""

    def __init__(self, failure_threshold: int = 5, reset_s: float = 5.0,
                 clock=time.monotonic) -> None:
        self.failure_threshold = failure_threshold
        self.reset_s = reset_s
        self._clock = clock
        self._lock = threading.Lock()
        self._breakers: dict[str, CircuitBreaker] = {}

    def breaker(self, key: str) -> CircuitBreaker:
        with self._lock:
            b = self._breakers.get(key)
            if b is None:
                b = self._breakers[key] = CircuitBreaker(
                    self.failure_threshold, self.reset_s,
                    clock=self._clock, name=key)
            return b

    def is_open(self, key: str) -> bool:
        with self._lock:
            b = self._breakers.get(key)
        return b.is_open() if b is not None else False

    def open_count(self) -> int:
        with self._lock:
            bs = list(self._breakers.values())
        return sum(1 for b in bs if b.is_open())

    def prune(self, live) -> None:
        """Forget breakers for departed workers: a rejoining worker
        (same URL) starts with a clean slate, like its fresh session."""
        with self._lock:
            for key in list(self._breakers):
                if key not in live:
                    del self._breakers[key]

    def snapshot(self) -> dict[str, str]:
        with self._lock:
            bs = dict(self._breakers)
        return {k: b.state for k, b in bs.items()}


class ClusterResilience:
    """The node's resilience bundle: one retry policy + one breaker
    board + per-worker latency EWMAs (gray-failure detection), built
    from :class:`~tfidf_tpu.utils.config.Config` knobs and shared by
    every leader→worker RPC path."""

    # EWMA smoothing for the gray-failure detector: ~5-call memory,
    # heavy enough that one outlier RPC cannot trip a healthy worker
    _SLOW_ALPHA = 0.2

    def __init__(self, config) -> None:
        self.policy = RetryPolicy(
            max_attempts=config.rpc_max_attempts,
            base_delay_s=config.rpc_backoff_base_s,
            max_delay_s=config.rpc_backoff_max_s,
            deadline_s=config.rpc_retry_deadline_s)
        self.board = BreakerBoard(
            failure_threshold=config.breaker_failure_threshold,
            reset_s=config.breaker_reset_s)
        # gray-failure detection (nemesis latency injection, overloaded
        # or swapping workers): a slow-but-ALIVE worker never fails a
        # call, so the consecutive-failure breaker stays closed while
        # every scatter it owns drags to its deadline. Track a
        # successful-call latency EWMA per worker and trip the breaker
        # (breaker_slow_trips) when it crosses the threshold.
        self.slow_threshold_s = config.breaker_slow_threshold_ms / 1e3
        self.slow_min_samples = max(1, config.breaker_slow_min_samples)
        self._lat_lock = threading.Lock()
        self._lat: dict[str, tuple[float, int]] = {}   # worker -> (ewma, n)

    def prune(self, live) -> None:
        """Forget breakers AND latency EWMAs for departed workers."""
        self.board.prune(live)
        if self.slow_threshold_s > 0:
            with self._lat_lock:
                for key in list(self._lat):
                    if key not in live:
                        del self._lat[key]

    def latency_snapshot(self) -> dict[str, tuple[float, int]]:
        """Copy of the per-worker successful-call latency EWMAs:
        ``{worker: (ewma_seconds, samples)}``. The SLO autopilot's
        slow-trip controller derives ``breaker_slow_threshold_ms``
        from the cross-worker spread of these."""
        with self._lat_lock:
            return dict(self._lat)

    def _note_latency(self, worker: str, dt_s: float) -> None:
        if self.slow_threshold_s <= 0:
            return
        with self._lat_lock:
            ewma, n = self._lat.get(worker, (0.0, 0))
            ewma = dt_s if n == 0 else (
                self._SLOW_ALPHA * dt_s + (1.0 - self._SLOW_ALPHA) * ewma)
            n += 1
            trip = (n >= self.slow_min_samples
                    and ewma > self.slow_threshold_s)
            # on trip the EWMA restarts: the half-open probe after
            # reset_s must judge the worker fresh, not against the
            # slow era that condemned it
            self._lat[worker] = (0.0, 0) if trip else (ewma, n)
        if trip:
            global_metrics.inc("breaker_slow_trips")
            log.warning("worker latency EWMA over threshold; tripping "
                        "breaker (gray failure)", target=worker,
                        ewma_ms=round(ewma * 1e3, 1),
                        threshold_ms=round(self.slow_threshold_s * 1e3,
                                           1))
            self.board.breaker(worker).trip_slow()

    def worker_call(self, worker: str, fn, retry: bool = True,
                    track_latency: bool = False):
        """Run one logical RPC against ``worker`` under its breaker.

        The breaker admits/rejects the WHOLE logical call; the retry
        policy runs inside it, so a call that succeeds on attempt 2 of 3
        counts as one breaker success, and only a call that exhausts its
        retries counts as one breaker failure. Application rejections
        (4xx) propagate without indicting the worker.

        ``track_latency=True`` feeds the gray-failure EWMA (see
        ``_note_latency``) — opt-in, for the SCATTER-path call sites
        only: a single EWMA mixing ms-scale scatter RPCs with
        legitimately-minutes-long bulk uploads would condemn a healthy
        worker for doing bulk work. The sample is the successful
        attempt's OWN duration (measured inside ``fn``'s wrapper), so
        retry backoff sleeps and failed-attempt timeouts never
        inflate it."""
        b = self.board.breaker(worker)
        b.acquire()
        run = fn
        measured: list[float] = []
        if track_latency and self.slow_threshold_s > 0:
            def run() -> object:
                t0 = time.monotonic()
                out = fn()
                measured.append(time.monotonic() - t0)
                return out
        try:
            out = self.policy.call(run) if retry else run()
        except Exception as e:
            if isinstance(e, DeadlineExpired):
                b.release()   # never dispatched: no evidence either way
            elif is_worker_fault(e):
                b.record_failure()
            else:
                b.record_success()   # a 4xx proves the worker is alive
            raise
        b.record_success()
        if measured:
            # AFTER the breaker success accounting: a slow trip fired
            # here must not be immediately re-closed by it
            self._note_latency(worker, measured[-1])
        return out

"""Rank fusion for the hybrid query plan — pure, dependency-free math.

Both algorithms operate on the two per-stage GLOBAL top-k lists the
scatter owner-merge produces (each doc is owned by exactly one worker,
so per-stage merges are exact; fusing exact lists is itself exact and
matches a single-node oracle bit-for-bit).  Everything here is plain
python on <= 2k tuples per query — no arrays, no device — so the same
functions ARE the reference the tier-1 fusion-algebra oracle checks
against (tests/test_hybrid.py re-derives them independently).

Determinism contract shared with the whole query plane: ranking order
is ``(-score, name)`` — ties break alphabetically, everywhere.
"""

from __future__ import annotations

from typing import Dict, List, Mapping, Sequence, Tuple

FUSION_METHODS = ("rrf", "wsum")


def rank_list(merged: Mapping[str, float], k: int
              ) -> List[Tuple[str, float]]:
    """Top-k of a name->score map in the plane's canonical order."""
    return sorted(merged.items(), key=lambda kv: (-kv[1], kv[0]))[:k]


def fuse_rrf(sparse: Sequence[Tuple[str, float]],
             dense: Sequence[Tuple[str, float]],
             *, rrf_k: float = 60.0, w_sparse: float = 0.5,
             w_dense: float = 0.5) -> Dict[str, float]:
    """Reciprocal-rank fusion: score = sum_stage w / (rrf_k + rank),
    ranks 1-based within each stage's top-k list. Rank-only — immune to
    the stages' incomparable score scales (BM25 vs cosine)."""
    fused: Dict[str, float] = {}
    for weight, ranked in ((w_sparse, sparse), (w_dense, dense)):
        for rank, (name, _score) in enumerate(ranked, start=1):
            fused[name] = fused.get(name, 0.0) + weight / (rrf_k + rank)
    return fused


def _minmax(ranked: Sequence[Tuple[str, float]]) -> Dict[str, float]:
    if not ranked:
        return {}
    scores = [s for _, s in ranked]
    lo, hi = min(scores), max(scores)
    if hi <= lo:
        # all tied at the top of their stage: full credit, not 0/0
        return {n: 1.0 for n, _ in ranked}
    span = hi - lo
    return {n: (s - lo) / span for n, s in ranked}


def fuse_weighted(sparse: Sequence[Tuple[str, float]],
                  dense: Sequence[Tuple[str, float]],
                  *, w_sparse: float = 0.5, w_dense: float = 0.5
                  ) -> Dict[str, float]:
    """Weighted sum of min-max-normalized stage scores (normalized over
    each stage's own top-k list); a doc absent from a stage contributes
    0 from it."""
    ns, nd = _minmax(sparse), _minmax(dense)
    fused: Dict[str, float] = {}
    for name in set(ns) | set(nd):
        fused[name] = (w_sparse * ns.get(name, 0.0)
                       + w_dense * nd.get(name, 0.0))
    return fused


def fuse(sparse_merged: Mapping[str, float],
         dense_merged: Mapping[str, float], *, method: str, k: int,
         rrf_k: float = 60.0, w_sparse: float = 0.5,
         w_dense: float = 0.5) -> Dict[str, float]:
    """Fuse the two per-stage merged score maps into one name->score
    map (the caller re-ranks it with the plane's usual ordering)."""
    sparse_ranked = rank_list(sparse_merged, k)
    dense_ranked = rank_list(dense_merged, k)
    if method == "rrf":
        return fuse_rrf(sparse_ranked, dense_ranked, rrf_k=rrf_k,
                        w_sparse=w_sparse, w_dense=w_dense)
    if method == "wsum":
        return fuse_weighted(sparse_ranked, dense_ranked,
                             w_sparse=w_sparse, w_dense=w_dense)
    raise ValueError(
        f"unknown fusion method {method!r}; known: {FUSION_METHODS}")

"""Durable storage for the coordination substrate: WAL + snapshots.

The reference gets durability for free from ZooKeeper — every accepted
write lands in ZooKeeper's transaction log and fuzzy snapshots before it
is acknowledged (``ZookeeperConfig.java:15-21`` just points at the
ensemble). The framework's substrate (``cluster/coordination.py``) was a
single in-memory process until now; this module supplies the missing
persistence layer, following the ZooKeeper/Raft design split:

- :class:`DurableStore` — one directory holding

  * ``wal.log``      — CRC-framed append-only log of state-machine
    commands (``{"i": index, "t": term, "c": cmd}`` JSON payloads).
    Recovery replays frames and *truncates at the first corrupt or
    short frame* — a torn tail from a crash mid-append loses only the
    unacknowledged suffix, never the committed prefix.
  * ``snapshot.json`` — atomically-replaced full snapshot of the znode
    tree + session table at some applied index (log compaction point).
  * ``meta.json``     — Raft hard state (``term``, ``voted_for``),
    fsynced before any vote or append response leaves the node.

Frame format (little-endian): ``<II`` = (payload length, CRC32 of
payload) followed by the JSON payload. fsync policy: ``fsync=True``
(default) syncs every append batch before it is acknowledged — the
Raft/ZooKeeper contract; ``fsync=False`` trades the tail-loss window for
throughput (tests, ephemeral deployments).

Fault points: ``wal.append``, ``wal.fsync``, ``wal.snapshot`` (see
``utils/faults.KNOWN_FAULT_POINTS``).
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from typing import Any

from tfidf_tpu.utils.storage import (atomic_write_bytes,
                                     atomic_write_json, read_json)
from tfidf_tpu.utils.faults import global_injector
from tfidf_tpu.utils.logging import get_logger
from tfidf_tpu.utils.metrics import global_metrics

log = get_logger("cluster.wal")

_FRAME = struct.Struct("<II")   # (payload_len, crc32)

WAL_FILE = "wal.log"
SNAPSHOT_FILE = "snapshot.json"
META_FILE = "meta.json"


def encode_frame(payload: bytes) -> bytes:
    return _FRAME.pack(len(payload), zlib.crc32(payload)) + payload


def decode_frames(blob: bytes) -> tuple[list[bytes], int]:
    """Decode consecutive frames; returns (payloads, clean_prefix_len).

    Stops at the first short or CRC-mismatched frame — everything after
    a torn write is unacknowledged by construction (append fsyncs before
    ack) and is discarded on recovery.
    """
    out: list[bytes] = []
    off = 0
    n = len(blob)
    while off + _FRAME.size <= n:
        length, crc = _FRAME.unpack_from(blob, off)
        start = off + _FRAME.size
        end = start + length
        if end > n:
            break                      # torn tail
        payload = blob[start:end]
        if zlib.crc32(payload) != crc:
            break                      # corrupt frame
        out.append(payload)
        off = end
    return out, off


class DurableStore:
    """WAL + snapshot + hard-state files under one ``data_dir``."""

    def __init__(self, data_dir: str, fsync: bool = True) -> None:
        self.dir = data_dir
        self.fsync = fsync
        os.makedirs(data_dir, exist_ok=True)
        self._wal_path = os.path.join(data_dir, WAL_FILE)
        self._snap_path = os.path.join(data_dir, SNAPSHOT_FILE)
        self._meta_path = os.path.join(data_dir, META_FILE)
        self._fh = open(self._wal_path, "ab")

    # ---- recovery ----

    def load(self) -> tuple[dict, dict | None, list[dict]]:
        """Returns ``(meta, snapshot_or_None, entries)``.

        ``meta``     — ``{"term": int, "voted_for": str|None}``
        ``snapshot`` — ``{"last_index", "last_term", "state"}``
        ``entries``  — WAL entries ``{"i", "t", "c"}`` in index order;
        entries at or below the snapshot's ``last_index`` are dropped,
        and the file is truncated at the first corrupt frame.
        """
        meta = {"term": 0, "voted_for": None}
        if os.path.exists(self._meta_path):
            try:
                # checksummed read (utils/storage.py): bit rot in the
                # hard state is detected, not parsed — a flipped digit
                # in `term` is valid JSON that re-votes in a past term.
                # StorageCorruption is a ValueError: caught below.
                meta.update(read_json(self._meta_path))
            except (ValueError, OSError) as e:
                log.warning("raft meta unreadable; starting at term 0",
                            err=repr(e))
        snapshot: dict | None = None
        if os.path.exists(self._snap_path):
            try:
                snapshot = read_json(self._snap_path)
                if not {"last_index", "last_term",
                        "state"} <= set(snapshot):
                    raise ValueError("snapshot missing fields")
            except (ValueError, OSError) as e:
                log.warning("snapshot unreadable; replaying full WAL",
                            err=repr(e))
                snapshot = None
        with open(self._wal_path, "rb") as f:
            blob = f.read()
        payloads, clean = decode_frames(blob)
        if clean < len(blob):
            global_metrics.inc("wal_truncated_bytes", len(blob) - clean)
            log.warning("WAL tail truncated on recovery",
                        dropped_bytes=len(blob) - clean)
            self._fh.close()
            with open(self._wal_path, "r+b") as f:
                f.truncate(clean)
                os.fsync(f.fileno())
            self._fh = open(self._wal_path, "ab")
        base = snapshot["last_index"] if snapshot else 0
        entries: list[dict] = []
        expect = None
        for p in payloads:
            try:
                e = json.loads(p)
            except ValueError:
                break
            if e["i"] <= base:
                continue
            if expect is not None and e["i"] != expect:
                log.warning("WAL index gap; dropping suffix",
                            expected=expect, got=e["i"])
                break
            entries.append(e)
            expect = e["i"] + 1
        global_metrics.inc("wal_recovered_entries", len(entries))
        return meta, snapshot, entries

    # ---- appends ----

    def append(self, entries: list[dict]) -> None:
        """Frame + write + (policy) fsync a batch of entries. Raises on
        any I/O or injected fault — the caller must NOT acknowledge, and
        the file is rewound to its pre-append length so the failed
        frame cannot survive on disk (a leftover frame would reuse its
        index on the next append and recovery's index-continuity check
        would then truncate ACKED history after the duplicate)."""
        global_injector.check("wal.append")
        buf = b"".join(
            encode_frame(json.dumps(e, separators=(",", ":")).encode())
            for e in entries)
        # O_APPEND offset semantics make tell() unreliable pre-write
        start = os.fstat(self._fh.fileno()).st_size
        try:
            self._fh.write(buf)
            self._fh.flush()
            if self.fsync:
                global_injector.check("wal.fsync")
                os.fsync(self._fh.fileno())
                global_metrics.inc("wal_fsyncs")
        except Exception:
            try:
                self._fh.truncate(start)
                self._fh.flush()
                os.fsync(self._fh.fileno())
            except OSError:
                # disk refuses even the rewind: reopen so the next
                # append sees the true end-of-file
                self._fh.close()
                with open(self._wal_path, "r+b") as f:
                    f.truncate(start)
                self._fh = open(self._wal_path, "ab")
            raise
        global_metrics.inc("wal_appends", len(entries))

    # ---- rewrite paths (truncation + compaction) ----

    def rewrite(self, entries: list[dict]) -> None:
        """Atomically replace the WAL with exactly ``entries`` (conflict
        truncation after a leader change; compaction after snapshot) —
        temp + fsync + rename through the durable-IO seam."""
        buf = b"".join(
            encode_frame(json.dumps(e, separators=(",", ":")).encode())
            for e in entries)
        self._fh.close()
        try:
            atomic_write_bytes(self._wal_path, buf, fsync=True)
        finally:
            # reopen even when the seam write fails (ENOSPC, armed
            # nemesis): the atomic publish left the old log intact, and
            # a permanently-closed handle would crash every later
            # append with a non-OSError nothing upstream classifies
            self._fh = open(self._wal_path, "ab")
        global_metrics.inc("wal_rewrites")

    def write_snapshot(self, state: dict, last_index: int,
                       last_term: int) -> None:
        """Atomically persist a snapshot at ``last_index`` (the slow
        half: full-state JSON + fsync; callers may run it outside
        their locks — it touches only the snapshot file). Checksummed
        through the durable-IO seam, so the frame-checksummed WAL is no
        longer the only coordination file that can PROVE its bytes."""
        global_injector.check("wal.snapshot")
        atomic_write_json(
            self._snap_path,
            {"last_index": last_index, "last_term": last_term,
             "state": state})
        global_metrics.inc("wal_snapshots")

    def save_snapshot(self, state: dict, last_index: int, last_term: int,
                      remaining: list[dict]) -> None:
        """Snapshot at ``last_index`` and compact the WAL down to
        ``remaining`` (entries above the snapshot) in one step."""
        self.write_snapshot(state, last_index, last_term)
        self.rewrite(remaining)
        log.info("snapshot saved", last_index=last_index,
                 wal_entries=len(remaining))

    # ---- Raft hard state ----

    def set_meta(self, term: int, voted_for: str | None) -> None:
        """Persist (term, voted_for) BEFORE any vote/append response —
        a node must never vote twice in a term across a restart."""
        atomic_write_json(
            self._meta_path, {"term": term, "voted_for": voted_for})

    def close(self) -> None:
        try:
            self._fh.close()
        except OSError:
            pass



"""Leader election — the reference's predecessor-watch algorithm.

Re-implements ``leader/LeaderElection.java:14-114`` on the framework's own
coordination substrate: each candidate creates an ephemeral-sequential znode
under ``/election`` (``:49-55``); the smallest sequence number is the
leader; every other candidate watches only its immediate predecessor (no
herd effect, ``:57-86``); a ``NodeDeleted`` event triggers re-election
(``:100-113``). Role transitions fire an :class:`OnElectionCallback`
(``leader/OnElectionCallback.java:3-8``).
"""

from __future__ import annotations

import threading
from typing import Protocol

from tfidf_tpu.cluster.coordination import (NODE_DELETED, EPHEMERAL_SEQUENTIAL,
                                            CoordinationClient, Event,
                                            LocalCoordination, NoNodeError)
from tfidf_tpu.utils.logging import get_logger

log = get_logger("cluster.election")

ELECTION_NAMESPACE = "/election"
CANDIDATE_PREFIX = "c_"


class OnElectionCallback(Protocol):
    """Two-method role-transition interface
    (``leader/OnElectionCallback.java:3-8``)."""

    def on_elected_to_be_leader(self) -> None: ...
    def on_worker(self) -> None: ...


class LeaderElection:
    def __init__(self, coord: "LocalCoordination | CoordinationClient",
                 callback: OnElectionCallback) -> None:
        self.coord = coord
        self.callback = callback
        self.znode: str | None = None       # full path of my candidate node
        self._lock = threading.Lock()       # serializes re-elections

    # ``LeaderElection.initializeElectionNode`` (:30-47)
    def initialize(self) -> None:
        self.coord.ensure(ELECTION_NAMESPACE)

    # ``volunteerForLeadership`` (:49-55)
    def volunteer_for_leadership(self) -> None:
        self.initialize()
        self.znode = self.coord.create(
            f"{ELECTION_NAMESPACE}/{CANDIDATE_PREFIX}",
            mode=EPHEMERAL_SEQUENTIAL)
        log.info("volunteered", znode=self.znode)

    @property
    def _my_name(self) -> str:
        assert self.znode is not None, "volunteer_for_leadership first"
        return self.znode.rsplit("/", 1)[1]

    def epoch(self) -> int | None:
        """Monotonic leadership epoch: this candidate's own sequence
        number, parsed from the ephemeral-sequential znode name. The
        leader is the SMALLEST live candidate and the parent's counter
        only grows, so every successive leader's epoch strictly
        increases across failovers, resignations, and rejoins — the
        fencing token the mutating data plane stamps as
        ``X-Leader-Epoch`` (cluster/fencing.py). None before
        volunteering (or after resigning)."""
        if self.znode is None:
            return None
        suffix = self._my_name[len(CANDIDATE_PREFIX):]
        return int(suffix) if suffix.isdigit() else None

    # ``reelectLeader`` (:57-86): loop until we are leader or hold a watch
    # on a live predecessor (the predecessor may vanish between the listing
    # and the watch registration — same retry loop as the reference).
    def reelect_leader(self) -> None:
        with self._lock:
            while True:
                children = self.coord.get_children(ELECTION_NAMESPACE)
                me = self._my_name
                if me not in children:   # our session lapsed: not a member
                    log.warning("own candidate znode gone", znode=self.znode)
                    return
                if children[0] == me:
                    log.info("elected leader", znode=self.znode)
                    self.callback.on_elected_to_be_leader()
                    return
                pred = children[children.index(me) - 1]
                pred_path = f"{ELECTION_NAMESPACE}/{pred}"
                if self.coord.exists(pred_path, watcher=self._on_pred_event):
                    log.info("watching predecessor", me=me, predecessor=pred)
                    self.callback.on_worker()
                    return
                # predecessor died in the window: retry

    # ``process(WatchedEvent)`` (:100-113)
    def _on_pred_event(self, ev: Event) -> None:
        if ev.type == NODE_DELETED:
            self.reelect_leader()

    # ``isLeader`` (:88-97) — recomputed from the live children, not cached
    def is_leader(self) -> bool:
        if self.znode is None:
            return False
        try:
            children = self.coord.get_children(ELECTION_NAMESPACE)
        except NoNodeError:
            return False
        return bool(children) and children[0] == self._my_name

    def resign(self) -> None:
        """Delete own candidate node (used by graceful shutdown and fault
        injection; the reference only ever resigns by dying)."""
        if self.znode is not None:
            try:
                self.coord.delete(self.znode)
            except NoNodeError:
                pass
            self.znode = None

"""Compact binary wire format for batched scatter-gather results.

The reference moves one query per HTTP request and serializes every hit as
a JSON object (``{"document":{"name":..},"score":..}`` — the Jackson wire
shape of ``DocumentScoreInfo``, ``Leader.java:54-77``). At cluster QPS in
the thousands that per-hit JSON encode/decode is the dominant Python cost
on both sides of the wire, so the batched worker RPC
(``POST /worker/process-batch``) answers in this packed layout instead:

    u32 magic       format tag/version (``MAGIC``)
    u32 n_queries
    u32 counts[n_queries]     hits per query, in request order
    u32 total                 sum(counts)  (redundant; integrity check)
    f32 scores[total]
    u32 name_lens[total]
    u8  names[...]            concatenated UTF-8 names

Scores and lengths decode on the receiving side as two ``np.frombuffer``
views — no per-hit float parsing — and names slice out of one blob. The
per-query JSON path (``/worker/process``) keeps the reference-compatible
shape; this format is internal to the leader<->worker scatter.
"""

from __future__ import annotations

import struct

import numpy as np

MAGIC = 0x54504231   # "TPB1"

_HEADER = struct.Struct("<II")
_U32 = struct.Struct("<I")


def pack_hit_lists(results) -> bytes:
    """Serialize ``list[list[SearchHit | (name, score)]]``."""
    counts = np.fromiter((len(r) for r in results), np.uint32,
                         count=len(results))
    total = int(counts.sum())
    scores = np.empty(total, np.float32)
    lens = np.empty(total, np.uint32)
    names: list[bytes] = []
    i = 0
    for r in results:
        for name, score in r:
            b = name.encode("utf-8")
            names.append(b)
            lens[i] = len(b)
            scores[i] = score
            i += 1
    return b"".join((_HEADER.pack(MAGIC, len(results)), counts.tobytes(),
                     _U32.pack(total), scores.tobytes(), lens.tobytes(),
                     b"".join(names)))


def pack_topk_arrays(vals, ids, names) -> bytes:
    """Serialize raw top-k result arrays straight into the wire layout —
    the serving fast path (``Searcher.search_arrays`` ->
    ``/worker/process-batch`` reply) that skips building per-hit
    ``SearchHit`` objects entirely.

    ``vals [N, k] f32`` / ``ids [N, k] i32`` are one exact top-k per
    query in score-descending column order; ``ids`` index ``names``.
    Entries with a non-finite or <= 0 value are dead (padding / no
    match) and are dropped, exactly as the hit-assembly path drops
    them, so the produced bytes are identical to
    ``pack_hit_lists(assembled_hits)`` for score-ordered results (the
    parity gate in ``tests/test_pipeline.py`` holds this).
    """
    vals = np.asarray(vals, np.float32)
    ids = np.asarray(ids)
    live = np.isfinite(vals) & (vals > 0.0)
    counts = live.sum(axis=1, dtype=np.uint32)
    # boolean-mask flattening is row-major: query order preserved,
    # within-query order stays score-descending (the top-k column order)
    scores = np.ascontiguousarray(vals[live])
    name_blobs = [names[d].encode("utf-8") for d in ids[live].tolist()]
    total = len(name_blobs)
    lens = np.fromiter(map(len, name_blobs), np.uint32, count=total)
    return b"".join((_HEADER.pack(MAGIC, vals.shape[0]),
                     counts.tobytes(), _U32.pack(total),
                     scores.tobytes(), lens.tobytes(),
                     b"".join(name_blobs)))


def unpack_hit_lists(data: bytes) -> list[list[tuple[str, float]]]:
    """Decode :func:`pack_hit_lists` output into per-query
    ``[(name, score), ...]`` lists (request order)."""
    # the wire contract is ValueError on ANY malformed buffer; without
    # the up-front length checks a truncated reply surfaces as
    # struct.error from unpack_from instead
    if len(data) < _HEADER.size:
        raise ValueError(
            f"wire buffer too short for header: {len(data)} bytes")
    magic, n = _HEADER.unpack_from(data, 0)
    if magic != MAGIC:
        raise ValueError(f"bad wire magic {magic:#x}")
    off = _HEADER.size
    if len(data) < off + 4 * n + _U32.size:
        raise ValueError("wire buffer too short for counts")
    counts = np.frombuffer(data, np.uint32, count=n, offset=off)
    off += 4 * n
    (total,) = _U32.unpack_from(data, off)
    off += _U32.size
    if int(counts.sum()) != total:
        raise ValueError("wire counts do not sum to total")
    scores = np.frombuffer(data, np.float32, count=total, offset=off)
    off += 4 * total
    lens = np.frombuffer(data, np.uint32, count=total, offset=off)
    off += 4 * total
    ends = np.cumsum(lens) + off
    starts = ends - lens
    if total and int(ends[-1]) != len(data):
        raise ValueError("wire name blob length mismatch")
    out: list[list[tuple[str, float]]] = []
    i = 0
    for c in counts:
        hits = [(data[starts[j]:ends[j]].decode("utf-8"),
                 float(scores[j])) for j in range(i, i + int(c))]
        out.append(hits)
        i += int(c)
    return out

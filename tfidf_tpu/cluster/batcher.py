"""Server-side query micro-batching.

The scoring kernels are built for a padded ``[B]`` query batch
(:mod:`tfidf_tpu.ops.scoring`), but HTTP requests arrive one query at a
time — the reference scores them one at a time too (``Worker.java:175-186``,
one Lucene search per POST). Running each request as a batch of one leaves
most of the device batch idle. The :class:`QueryBatcher` coalesces
concurrent requests into one device batch: the first arrival waits a short
linger window for company, then the group is scored in a single
``search_batch`` call and results are fanned back to the waiting handler
threads.

Latency math: the linger adds at most ``linger_s`` (default 2 ms) to a lone
query — noise next to an HTTP round-trip — while under concurrent load B
queries cost one kernel launch instead of B.
"""

from __future__ import annotations

import threading
from collections import deque

from tfidf_tpu.utils.logging import get_logger
from tfidf_tpu.utils.metrics import global_metrics

log = get_logger("cluster.batcher")


class _Waiter:
    __slots__ = ("query", "k", "unbounded", "event", "result", "error")

    def __init__(self, query: str, k: int | None, unbounded: bool) -> None:
        self.query = query
        self.k = k
        self.unbounded = unbounded
        self.event = threading.Event()
        self.result = None
        self.error: BaseException | None = None


class QueryBatcher:
    """Coalesce concurrent search calls into device-sized batches.

    Thread-safe; callers block until their query's results are ready.
    Queries with differing (k, unbounded) parameters are grouped into
    separate batches (they need different post-processing), preserving
    arrival order within the queue.
    """

    def __init__(self, engine, max_batch: int = 32,
                 linger_s: float = 0.002, pipeline: int = 1) -> None:
        """``pipeline`` scorer threads run concurrent ``search_batch``
        calls (the engine is a pure function of its snapshot, so this is
        safe). On a high-RTT device link (remote-TPU tunnel) a second
        in-flight batch hides one batch's result fetch under the next
        batch's device compute — the same trick Searcher.search plays
        across chunks, applied across micro-batches."""
        self.engine = engine
        self.max_batch = max(1, max_batch)
        self.linger_s = linger_s
        self._lock = threading.Lock()
        self._items: deque[_Waiter] = deque()
        self._wake = threading.Event()
        self._stopping = False
        self._threads = [
            threading.Thread(target=self._run, daemon=True,
                             name=f"query-batcher-{i}")
            for i in range(max(1, pipeline))]
        for t in self._threads:
            t.start()

    def search(self, query: str, k: int | None = None,
               unbounded: bool = False):
        """Submit one query; returns its hit list (blocking)."""
        w = _Waiter(query, k, unbounded)
        # check-and-enqueue under the lock: a check outside it could pass
        # just before stop() drains the queue, leaving this waiter parked
        # forever (ADVICE r2)
        with self._lock:
            if self._stopping:
                raise RuntimeError("batcher stopped")
            self._items.append(w)
        self._wake.set()
        w.event.wait()
        if w.error is not None:
            raise w.error
        return w.result

    def stop(self) -> None:
        with self._lock:
            self._stopping = True
        self._wake.set()
        for t in self._threads:
            t.join(timeout=2.0)
        # fail any stragglers rather than hanging their handler threads
        with self._lock:
            items, self._items = list(self._items), deque()
        for w in items:
            w.error = RuntimeError("batcher stopped")
            w.event.set()

    # ---- batcher thread ----

    def _run(self) -> None:
        while True:
            self._wake.wait()
            if self._stopping:
                return
            # linger: give concurrent requests a moment to pile up so the
            # device batch fills; a lone query pays at most linger_s
            if self.linger_s > 0:
                threading.Event().wait(self.linger_s)
            batch = self._take_batch()
            if not batch:
                continue
            try:
                results = self.engine.search_batch(
                    [w.query for w in batch],
                    k=batch[0].k, unbounded=batch[0].unbounded)
                for w, r in zip(batch, results):
                    w.result = r
            except Exception as e:
                for w in batch:
                    w.error = e
            for w in batch:
                w.event.set()
            global_metrics.inc("query_batches")
            global_metrics.set_gauge("last_query_batch_size", len(batch))

    def _take_batch(self) -> list[_Waiter]:
        """Pop the head group: leading queued items sharing the head's
        (k, unbounded), up to max_batch. Items with other parameters stay
        queued in order for the next round."""
        with self._lock:
            if not self._items:
                if not self._stopping:
                    # never clear after stop() set the event, or sibling
                    # pipeline threads park in _wake.wait() forever
                    self._wake.clear()
                return []
            first = self._items.popleft()
            batch = [first]
            while (self._items and len(batch) < self.max_batch
                   and (self._items[0].k, self._items[0].unbounded)
                   == (first.k, first.unbounded)):
                batch.append(self._items.popleft())
            if not self._items and not self._stopping:
                # never clear after stop() set the event, or sibling
                # pipeline threads park in _wake.wait() forever
                self._wake.clear()
        return batch

"""Server-side query micro-batching.

The scoring kernels are built for a padded ``[B]`` query batch
(:mod:`tfidf_tpu.ops.scoring`), but HTTP requests arrive one query at a
time — the reference scores them one at a time too (``Worker.java:175-186``,
one Lucene search per POST). Running each request as a batch of one leaves
most of the device batch idle. The :class:`QueryBatcher` coalesces
concurrent requests into one device batch: the first arrival waits a short
linger window for company, then the group is scored in a single
``search_batch`` call and results are fanned back to the waiting handler
threads.

Latency math: the linger adds at most ``linger_s`` (default 2 ms) to a lone
query — noise next to an HTTP round-trip — while under concurrent load B
queries cost one kernel launch instead of B.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque

from tfidf_tpu.utils.logging import get_logger
from tfidf_tpu.utils.metrics import global_metrics
from tfidf_tpu.utils.tracing import current_span, global_tracer

log = get_logger("cluster.batcher")


class _Waiter:
    __slots__ = ("query", "event", "result", "error", "t0", "key",
                 "lane", "span")

    def __init__(self, query, lane: int = 0) -> None:
        self.query = query   # the submitted item (any shape)
        self.event = threading.Event()
        self.result = None
        self.error: BaseException | None = None
        self.t0 = 0.0   # submit time (linger accounting)
        self.key = None  # group key, stamped at SUBMIT time
        self.lane = lane  # 0 = interactive, 1 = bulk (weighted dequeue)
        self.span = None  # the submitter's active trace span (if any)


class Coalescer:
    """Generic request coalescer: concurrent ``submit(item)`` calls group
    into batches handed to ``batch_fn(items) -> results`` (positional,
    same length). The leader's scatter path uses this to turn N
    concurrent ``/leader/start`` requests into ONE batched RPC per
    worker; the per-item linger wait is recorded as the
    ``{name}_linger`` timing so the serving-path breakdown can attribute
    queueing delay separately from RPC time.

    ``pipeline`` dispatcher threads let one batch's RPC round trip
    overlap the next batch's formation.

    Two priority lanes (``submit(item, lane=...)``): lane 0
    (interactive) and lane 1 (bulk). Batch formation is a WEIGHTED
    dequeue — the interactive queue always fills first (so bulk can
    never starve interactive: every dispatch round that finds an
    interactive item queued dispatches it), but while interactive
    traffic saturates a batch, ``bulk_share`` of the slots are reserved
    for queued bulk items so bulk starves neither. Unused reservation
    in either direction is returned to the other lane."""

    def __init__(self, batch_fn, *, max_batch: int = 128,
                 linger_s: float = 0.002, pipeline: int = 2,
                 name: str = "coalesce", group_key=None,
                 linger_min_s: float | None = None,
                 linger_max_s: float | None = None,
                 bulk_share: float = 0.25) -> None:
        """``group_key(item)``, when given, keeps a batch homogeneous:
        only leading queued items sharing the head's key join it; the
        rest stay queued in order for the next dispatcher round. The
        key is evaluated ONCE, at submit time — so a key derived from
        ambient state (the leader's membership epoch) partitions
        batches by the world the caller saw, not by whatever the
        dispatcher sees later.

        ``linger_min_s``/``linger_max_s`` arm the ADAPTIVE linger: with
        no batch in flight the dispatcher lingers only ``linger_min_s``
        (the executor downstream is idle — waiting would buy batch fill
        at the cost of idle device time), and as the in-flight count
        approaches the dispatcher pipeline depth the linger stretches
        toward ``linger_max_s`` (the device is saturated; fuller
        batches amortize better and the wait hides under in-flight
        work). Leaving them ``None`` keeps the fixed ``linger_s``."""
        self.batch_fn = batch_fn
        self.max_batch = max(1, max_batch)
        self.linger_s = linger_s
        self._linger_lo = linger_s if linger_min_s is None else linger_min_s
        self._linger_hi = linger_s if linger_max_s is None else linger_max_s
        self.name = name
        self.group_key = group_key
        self.bulk_share = min(max(bulk_share, 0.0), 1.0)
        self._lock = threading.Lock()
        self._items: deque[_Waiter] = deque()   # lane 0: interactive
        self._bulk: deque[_Waiter] = deque()    # lane 1: bulk/batch
        self._wake = threading.Event()
        self._stopping = False
        self._dispatching = 0   # batch_fn calls in flight (adaptive linger)
        self._threads = [
            threading.Thread(target=self._run, daemon=True,
                             name=f"{name}-{i}")
            for i in range(max(1, pipeline))]
        for t in self._threads:
            t.start()

    def submit(self, item, lane: int = 0):
        w = _Waiter(item, lane=1 if lane else 0)
        w.t0 = time.perf_counter()
        # trace linkage: the batch this item lands in runs on a
        # dispatcher thread with no request context — capture the
        # submitter's span so the dispatched batch can LINK (not
        # parent) the requests it absorbed
        sp = current_span()
        if sp is not None and sp.sampled:
            w.span = sp
        if self.group_key is not None:
            w.key = self.group_key(item)
        with self._lock:
            if self._stopping:
                raise RuntimeError(f"{self.name} stopped")
            if not any(t.is_alive() for t in self._threads):
                # every dispatcher died without stop() (a BaseException
                # escaped _run): fail fast BEFORE enqueueing — under
                # steady load, abandoned waiters would otherwise grow
                # _items without bound
                raise RuntimeError(f"{self.name} dispatchers died")
            (self._bulk if w.lane else self._items).append(w)
        self._wake.set()
        # bounded-slice wait + shutdown check (graftcheck lockgraph
        # indefinite-wait audit): a dispatcher that died mid-batch must
        # not wedge this caller's thread forever. After stop(), queued
        # waiters are failed by stop() itself; an in-flight batch gets a
        # short grace to settle, then this caller fails loudly — and
        # removes its still-queued waiter so the deque cannot leak.
        while not w.event.wait(timeout=0.5):
            if self._stopping or not any(
                    t.is_alive() for t in self._threads):
                if not w.event.wait(timeout=2.0):
                    with self._lock:
                        try:
                            (self._bulk if w.lane
                             else self._items).remove(w)
                        except ValueError:
                            pass   # already popped into a batch
                    raise RuntimeError(
                        f"{self.name} "
                        + ("stopped" if self._stopping
                           else "dispatchers died"))
                break
        if w.error is not None:
            raise w.error
        return w.result

    def linger_bounds(self) -> tuple[float, float]:
        """Current adaptive-linger bounds ``(lo_s, hi_s)``."""
        return self._linger_lo, self._linger_hi

    def set_linger_bounds(self, lo_s: float | None = None,
                          hi_s: float | None = None) -> None:
        """Retune the adaptive-linger bounds live (the SLO autopilot's
        linger knob). Plain GIL-atomic float writes, matching the
        unlocked reads in ``_effective_linger_s`` — a dispatcher that
        reads one old and one new bound computes one slightly-off
        linger, which is harmless for a latency knob."""
        if lo_s is not None:
            self._linger_lo = lo_s
        if hi_s is not None:
            self._linger_hi = hi_s

    def backlog(self) -> int:
        """LIVE queued items beyond one batch's worth — the admission
        layer's stall-proof overload signal. The ``last_*_queue_depth``
        gauge is only refreshed at batch formation, so it freezes at
        its last value while every dispatcher thread is blocked inside
        a stalled ``batch_fn`` RPC — exactly when the queue grows
        fastest. This reads the deques directly (unlocked ``len`` is a
        single atomic read; an off-by-a-few heuristic is fine for a
        watermark). One batch's worth is subtracted because a healthy
        linger window legitimately accumulates up to ``max_batch``
        items that the next formation round will take."""
        return max(0, len(self._items) + len(self._bulk) - self.max_batch)

    def stop(self) -> None:
        with self._lock:
            self._stopping = True
        self._wake.set()
        for t in self._threads:
            t.join(timeout=2.0)
        with self._lock:
            items = list(self._items) + list(self._bulk)
            self._items, self._bulk = deque(), deque()
        for w in items:
            w.error = RuntimeError(f"{self.name} stopped")
            w.event.set()

    def _effective_linger_s(self) -> float:
        """Adaptive linger: scale between the configured bounds by how
        busy the OTHER dispatcher threads are. 0 in-flight batches ->
        lo (dispatch now, the device is idle); every sibling busy -> hi
        (the wait hides under in-flight work and buys batch fill).

        The deciding thread is never inside ``batch_fn`` itself, so the
        busy fraction is taken over the ``pipeline - 1`` siblings —
        dividing by ``pipeline`` would make ``hi`` unreachable. With a
        single dispatcher there are no siblings to read load from, so
        adaptation is moot and the fixed ``linger_s`` applies."""
        lo, hi = self._linger_lo, self._linger_hi
        if hi <= lo:
            return lo
        siblings = len(self._threads) - 1
        if siblings == 0:
            return self.linger_s
        with self._lock:
            busy = self._dispatching
        frac = min(busy / siblings, 1.0)
        return lo + (hi - lo) * frac

    def _run(self) -> None:
        while True:
            # bounded slice + shutdown re-check: a missed wake (or a
            # peer that never wakes us again) must not park this
            # dispatcher forever — the indefinite-wait audit's contract
            if not self._wake.wait(timeout=0.5):
                if self._stopping:
                    return
                continue
            if self._stopping:
                return
            linger = self._effective_linger_s()
            waited = 0.0   # the linger actually APPLIED (gauged below)
            if linger > 0:
                # linger only while the batch could still fill: at
                # saturation (a full batch already queued) the wait buys
                # nothing and would tax every query's latency
                with self._lock:
                    full = (len(self._items) + len(self._bulk)
                            >= self.max_batch)
                if not full:
                    threading.Event().wait(linger)
                    waited = linger
            with self._lock:
                batch = self._form_batch_locked()
                depth = len(self._items) + len(self._bulk)
                bulk_depth = len(self._bulk)
                if depth == 0 and not self._stopping:
                    # never clear after stop() set the event, or sibling
                    # dispatcher threads park in _wake.wait() forever
                    self._wake.clear()
            # queue depth LEFT BEHIND after this batch formed: the
            # serving-pressure signal the k8s HPA scales workers on
            # (deploy/k8s.yaml) AND the admission layer's backpressure
            # input (cluster/admission.py) — 0 in steady state, grows
            # when offered load outruns the dispatch pipeline
            global_metrics.set_gauge(f"last_{self.name}_queue_depth",
                                     depth)
            global_metrics.set_gauge(f"last_{self.name}_bulk_depth",
                                     bulk_depth)
            if not batch:
                continue
            try:
                self._dispatch_batch(batch, waited)
            except BaseException as e:
                # anything that escapes _dispatch_batch (BaseException
                # from batch_fn, a failure outside its Exception guard)
                # is about to kill THIS dispatcher thread — popped
                # waiters must never outlive it unsignaled, or their
                # submit() calls wedge until stop()
                for w in batch:
                    if not w.event.is_set():
                        w.error = RuntimeError(
                            f"{self.name} dispatcher died: {e!r}")
                        w.event.set()
                raise

    def _form_batch_locked(self) -> list[_Waiter]:
        """Weighted two-lane dequeue; caller holds ``self._lock``.

        The interactive head is popped FIRST whenever that lane is
        nonempty — so a dispatch round can never serve bulk while an
        interactive request waits (bulk starving interactive is
        impossible by construction). While interactive saturates the
        batch, ``bulk_share`` of the slots are reserved for
        key-compatible queued bulk items so bulk makes progress too;
        reservation either lane does not use returns to the other.
        Group-key homogeneity holds across lanes: the batch key is the
        first popped item's submit-time key, and only head items
        matching it (from either lane) join."""
        lead = self._items or self._bulk
        if not lead:
            return []
        first = lead.popleft()
        batch = [first]
        key = first.key   # stamped at submit time

        def head_ok(dq) -> bool:
            return bool(dq) and (self.group_key is None
                                 or dq[0].key == key)

        reserve = 0
        if first.lane == 0 and self.bulk_share > 0 and head_ok(self._bulk):
            reserve = max(1, int(self.max_batch * self.bulk_share))
        while head_ok(self._items) and len(batch) < self.max_batch - reserve:
            batch.append(self._items.popleft())
        while head_ok(self._bulk) and len(batch) < self.max_batch:
            batch.append(self._bulk.popleft())
        while head_ok(self._items) and len(batch) < self.max_batch:
            batch.append(self._items.popleft())
        return batch

    def _dispatch_batch(self, batch: list[_Waiter],
                        waited: float) -> None:
        t0 = time.perf_counter()
        for w in batch:   # queueing delay, attributed separately
            global_metrics.observe(f"{self.name}_linger", t0 - w.t0)
        # gauge the wait that actually happened: at saturation the
        # sleep is skipped, and reporting the computed linger there
        # would misattribute latency exactly where none was added
        global_metrics.set_gauge(f"last_{self.name}_linger_ms",
                                 round(waited * 1e3, 3))
        with self._lock:
            self._dispatching += 1
        # one batch span LINKED (not parented) to every traced request
        # it absorbed — the Dapper coalescing boundary: the batch serves
        # N independent traces, so it gets its OWN trace id, and each
        # request span links forward to it so a trace walk crosses the
        # boundary in either direction. Untraced batches (no submitter
        # had an active sampled span) skip tracing entirely.
        traced = [w.span for w in batch if w.span is not None]
        # sampled=True, never a re-roll: this root exists only because
        # the linked requests already won the sampling draw — an
        # independent draw would drop their scatter sub-trace with
        # probability (1 - sample_rate)
        batch_cm = (global_tracer.span(
            f"{self.name}.batch", sampled=True,
            links=[s.context for s in traced],
            attrs={"items": len(batch), "linked": len(traced)})
            if traced else contextlib.nullcontext())
        try:
            with batch_cm as bsp:
                if bsp is not None:
                    for s in traced:
                        s.add_link(bsp.context)
                results = self.batch_fn([w.query for w in batch])
            for w, r in zip(batch, results):
                w.result = r
        except Exception as e:
            # honest propagation: every coalesced caller sees the
            # SAME failure (never a fabricated empty success), and
            # the counter sizes the blast radius of one bad batch
            global_metrics.inc(f"{self.name}_batch_failures")
            for w in batch:
                w.error = e
        finally:
            with self._lock:
                self._dispatching -= 1
        for w in batch:
            w.event.set()
        global_metrics.observe(f"{self.name}_batch_total",
                               time.perf_counter() - t0)
        global_metrics.inc(f"{self.name}_batches")
        global_metrics.inc(f"{self.name}_items", len(batch))
        global_metrics.set_gauge(f"last_{self.name}_batch_size",
                                 len(batch))


class QueryBatcher(Coalescer):
    """Coalesce concurrent search calls into device-sized batches.

    Thread-safe; callers block until their query's results are ready.
    Queries with differing (k, unbounded) parameters are grouped into
    separate batches (they need different post-processing), preserving
    arrival order within the queue — the ``group_key`` hook of the
    generic :class:`Coalescer` this is built on.

    ``pipeline`` scorer threads run concurrent ``search_batch`` calls
    (the engine is a pure function of its snapshot, so this is safe). On
    a high-RTT device link (remote-TPU tunnel) a second in-flight batch
    hides one batch's result fetch under the next batch's device
    compute — the same trick Searcher.search plays across chunks,
    applied across micro-batches."""

    def __init__(self, engine, max_batch: int = 32,
                 linger_s: float = 0.002, pipeline: int = 1,
                 linger_min_s: float | None = None,
                 linger_max_s: float | None = None) -> None:
        self.engine = engine
        super().__init__(
            self._score, max_batch=max_batch, linger_s=linger_s,
            pipeline=pipeline, name="query",
            group_key=lambda item: (item[1], item[2]),
            linger_min_s=linger_min_s, linger_max_s=linger_max_s)

    def _score(self, items: list[tuple]) -> list:
        k, unbounded = items[0][1], items[0][2]
        return self.engine.search_batch(
            [it[0] for it in items], k=k, unbounded=unbounded)

    def search(self, query: str, k: int | None = None,
               unbounded: bool = False):
        """Submit one query; returns its hit list (blocking)."""
        return self.submit((query, k, unbounded))

"""Server-side query micro-batching.

The scoring kernels are built for a padded ``[B]`` query batch
(:mod:`tfidf_tpu.ops.scoring`), but HTTP requests arrive one query at a
time — the reference scores them one at a time too (``Worker.java:175-186``,
one Lucene search per POST). Running each request as a batch of one leaves
most of the device batch idle. The :class:`QueryBatcher` coalesces
concurrent requests into one device batch: the first arrival waits a short
linger window for company, then the group is scored in a single
``search_batch`` call and results are fanned back to the waiting handler
threads.

Latency math: the linger adds at most ``linger_s`` (default 2 ms) to a lone
query — noise next to an HTTP round-trip — while under concurrent load B
queries cost one kernel launch instead of B.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from tfidf_tpu.utils.logging import get_logger
from tfidf_tpu.utils.metrics import global_metrics

log = get_logger("cluster.batcher")


class _Waiter:
    __slots__ = ("query", "event", "result", "error", "t0", "key")

    def __init__(self, query) -> None:
        self.query = query   # the submitted item (any shape)
        self.event = threading.Event()
        self.result = None
        self.error: BaseException | None = None
        self.t0 = 0.0   # submit time (linger accounting)
        self.key = None  # group key, stamped at SUBMIT time


class Coalescer:
    """Generic request coalescer: concurrent ``submit(item)`` calls group
    into batches handed to ``batch_fn(items) -> results`` (positional,
    same length). The leader's scatter path uses this to turn N
    concurrent ``/leader/start`` requests into ONE batched RPC per
    worker; the per-item linger wait is recorded as the
    ``{name}_linger`` timing so the serving-path breakdown can attribute
    queueing delay separately from RPC time.

    ``pipeline`` dispatcher threads let one batch's RPC round trip
    overlap the next batch's formation."""

    def __init__(self, batch_fn, *, max_batch: int = 128,
                 linger_s: float = 0.002, pipeline: int = 2,
                 name: str = "coalesce", group_key=None,
                 linger_min_s: float | None = None,
                 linger_max_s: float | None = None) -> None:
        """``group_key(item)``, when given, keeps a batch homogeneous:
        only leading queued items sharing the head's key join it; the
        rest stay queued in order for the next dispatcher round. The
        key is evaluated ONCE, at submit time — so a key derived from
        ambient state (the leader's membership epoch) partitions
        batches by the world the caller saw, not by whatever the
        dispatcher sees later.

        ``linger_min_s``/``linger_max_s`` arm the ADAPTIVE linger: with
        no batch in flight the dispatcher lingers only ``linger_min_s``
        (the executor downstream is idle — waiting would buy batch fill
        at the cost of idle device time), and as the in-flight count
        approaches the dispatcher pipeline depth the linger stretches
        toward ``linger_max_s`` (the device is saturated; fuller
        batches amortize better and the wait hides under in-flight
        work). Leaving them ``None`` keeps the fixed ``linger_s``."""
        self.batch_fn = batch_fn
        self.max_batch = max(1, max_batch)
        self.linger_s = linger_s
        self._linger_lo = linger_s if linger_min_s is None else linger_min_s
        self._linger_hi = linger_s if linger_max_s is None else linger_max_s
        self.name = name
        self.group_key = group_key
        self._lock = threading.Lock()
        self._items: deque[_Waiter] = deque()
        self._wake = threading.Event()
        self._stopping = False
        self._dispatching = 0   # batch_fn calls in flight (adaptive linger)
        self._threads = [
            threading.Thread(target=self._run, daemon=True,
                             name=f"{name}-{i}")
            for i in range(max(1, pipeline))]
        for t in self._threads:
            t.start()

    def submit(self, item):
        w = _Waiter(item)
        w.t0 = time.perf_counter()
        if self.group_key is not None:
            w.key = self.group_key(item)
        with self._lock:
            if self._stopping:
                raise RuntimeError(f"{self.name} stopped")
            if not any(t.is_alive() for t in self._threads):
                # every dispatcher died without stop() (a BaseException
                # escaped _run): fail fast BEFORE enqueueing — under
                # steady load, abandoned waiters would otherwise grow
                # _items without bound
                raise RuntimeError(f"{self.name} dispatchers died")
            self._items.append(w)
        self._wake.set()
        # bounded-slice wait + shutdown check (graftcheck lockgraph
        # indefinite-wait audit): a dispatcher that died mid-batch must
        # not wedge this caller's thread forever. After stop(), queued
        # waiters are failed by stop() itself; an in-flight batch gets a
        # short grace to settle, then this caller fails loudly — and
        # removes its still-queued waiter so the deque cannot leak.
        while not w.event.wait(timeout=0.5):
            if self._stopping or not any(
                    t.is_alive() for t in self._threads):
                if not w.event.wait(timeout=2.0):
                    with self._lock:
                        try:
                            self._items.remove(w)
                        except ValueError:
                            pass   # already popped into a batch
                    raise RuntimeError(
                        f"{self.name} "
                        + ("stopped" if self._stopping
                           else "dispatchers died"))
                break
        if w.error is not None:
            raise w.error
        return w.result

    def stop(self) -> None:
        with self._lock:
            self._stopping = True
        self._wake.set()
        for t in self._threads:
            t.join(timeout=2.0)
        with self._lock:
            items, self._items = list(self._items), deque()
        for w in items:
            w.error = RuntimeError(f"{self.name} stopped")
            w.event.set()

    def _effective_linger_s(self) -> float:
        """Adaptive linger: scale between the configured bounds by how
        busy the OTHER dispatcher threads are. 0 in-flight batches ->
        lo (dispatch now, the device is idle); every sibling busy -> hi
        (the wait hides under in-flight work and buys batch fill).

        The deciding thread is never inside ``batch_fn`` itself, so the
        busy fraction is taken over the ``pipeline - 1`` siblings —
        dividing by ``pipeline`` would make ``hi`` unreachable. With a
        single dispatcher there are no siblings to read load from, so
        adaptation is moot and the fixed ``linger_s`` applies."""
        lo, hi = self._linger_lo, self._linger_hi
        if hi <= lo:
            return lo
        siblings = len(self._threads) - 1
        if siblings == 0:
            return self.linger_s
        with self._lock:
            busy = self._dispatching
        frac = min(busy / siblings, 1.0)
        return lo + (hi - lo) * frac

    def _run(self) -> None:
        while True:
            # bounded slice + shutdown re-check: a missed wake (or a
            # peer that never wakes us again) must not park this
            # dispatcher forever — the indefinite-wait audit's contract
            if not self._wake.wait(timeout=0.5):
                if self._stopping:
                    return
                continue
            if self._stopping:
                return
            linger = self._effective_linger_s()
            waited = 0.0   # the linger actually APPLIED (gauged below)
            if linger > 0:
                # linger only while the batch could still fill: at
                # saturation (a full batch already queued) the wait buys
                # nothing and would tax every query's latency
                with self._lock:
                    full = len(self._items) >= self.max_batch
                if not full:
                    threading.Event().wait(linger)
                    waited = linger
            with self._lock:
                batch = []
                if self._items:
                    first = self._items.popleft()
                    batch.append(first)
                    key = first.key   # stamped at submit time
                    while (self._items and len(batch) < self.max_batch
                           and (self.group_key is None
                                or self._items[0].key == key)):
                        batch.append(self._items.popleft())
                depth = len(self._items)
                if not self._items and not self._stopping:
                    # never clear after stop() set the event, or sibling
                    # dispatcher threads park in _wake.wait() forever
                    self._wake.clear()
            # queue depth LEFT BEHIND after this batch formed: the
            # serving-pressure signal the k8s HPA scales workers on
            # (deploy/k8s.yaml) — 0 in steady state, grows when offered
            # load outruns the dispatch pipeline
            global_metrics.set_gauge(f"last_{self.name}_queue_depth",
                                     depth)
            if not batch:
                continue
            try:
                self._dispatch_batch(batch, waited)
            except BaseException as e:
                # anything that escapes _dispatch_batch (BaseException
                # from batch_fn, a failure outside its Exception guard)
                # is about to kill THIS dispatcher thread — popped
                # waiters must never outlive it unsignaled, or their
                # submit() calls wedge until stop()
                for w in batch:
                    if not w.event.is_set():
                        w.error = RuntimeError(
                            f"{self.name} dispatcher died: {e!r}")
                        w.event.set()
                raise

    def _dispatch_batch(self, batch: list[_Waiter],
                        waited: float) -> None:
        t0 = time.perf_counter()
        for w in batch:   # queueing delay, attributed separately
            global_metrics.observe(f"{self.name}_linger", t0 - w.t0)
        # gauge the wait that actually happened: at saturation the
        # sleep is skipped, and reporting the computed linger there
        # would misattribute latency exactly where none was added
        global_metrics.set_gauge(f"last_{self.name}_linger_ms",
                                 round(waited * 1e3, 3))
        with self._lock:
            self._dispatching += 1
        try:
            results = self.batch_fn([w.query for w in batch])
            for w, r in zip(batch, results):
                w.result = r
        except Exception as e:
            # honest propagation: every coalesced caller sees the
            # SAME failure (never a fabricated empty success), and
            # the counter sizes the blast radius of one bad batch
            global_metrics.inc(f"{self.name}_batch_failures")
            for w in batch:
                w.error = e
        finally:
            with self._lock:
                self._dispatching -= 1
        for w in batch:
            w.event.set()
        global_metrics.observe(f"{self.name}_batch_total",
                               time.perf_counter() - t0)
        global_metrics.inc(f"{self.name}_batches")
        global_metrics.inc(f"{self.name}_items", len(batch))
        global_metrics.set_gauge(f"last_{self.name}_batch_size",
                                 len(batch))


class QueryBatcher(Coalescer):
    """Coalesce concurrent search calls into device-sized batches.

    Thread-safe; callers block until their query's results are ready.
    Queries with differing (k, unbounded) parameters are grouped into
    separate batches (they need different post-processing), preserving
    arrival order within the queue — the ``group_key`` hook of the
    generic :class:`Coalescer` this is built on.

    ``pipeline`` scorer threads run concurrent ``search_batch`` calls
    (the engine is a pure function of its snapshot, so this is safe). On
    a high-RTT device link (remote-TPU tunnel) a second in-flight batch
    hides one batch's result fetch under the next batch's device
    compute — the same trick Searcher.search plays across chunks,
    applied across micro-batches."""

    def __init__(self, engine, max_batch: int = 32,
                 linger_s: float = 0.002, pipeline: int = 1,
                 linger_min_s: float | None = None,
                 linger_max_s: float | None = None) -> None:
        self.engine = engine
        super().__init__(
            self._score, max_batch=max_batch, linger_s=linger_s,
            pipeline=pipeline, name="query",
            group_key=lambda item: (item[1], item[2]),
            linger_min_s=linger_min_s, linger_max_s=linger_max_s)

    def _score(self, items: list[tuple]) -> list:
        k, unbounded = items[0][1], items[0][2]
        return self.engine.search_batch(
            [it[0] for it in items], k=k, unbounded=unbounded)

    def search(self, query: str, k: int | None = None,
               unbounded: bool = False):
        """Submit one query; returns its hit list (blocking)."""
        return self.submit((query, k, unbounded))

"""Leader-side elastic rebalancing: crash-safe live shard migration,
splitting, and planned decommission (drain).

The reference's placement is static — the registry maps each document
to whichever worker the leader picked at upload time, forever
(``Leader.java:153-207``); a shard that outgrows its worker, or a
freshly joined worker, cannot be fixed without downtime. This module
composes the PR-5 primitives (durable :class:`PlacementMap`, the
``moved``/pending-delete reconcile machinery, the R-way upload fan-out,
per-request owner assignment) into live rebalancing that is safe under
crashes at every step.

**The staged state machine** (per migration, durable in the placement
znode):

``copying``
    The doc range is uploaded to the target replicas through the same
    upload/repair plumbing as anti-entropy repair; each confirmed leg
    is an ordinary NON-primary confirmed replica. Ownership never
    moves in this phase, so a crash of the leader, the source, or the
    target mid-copy loses nothing and double-counts nothing: a new
    leader aborts the record and the trim pass reclaims stray legs; a
    dead target just fails its legs; a dead source is handled by the
    ordinary death path (the half-copied targets may by then be the
    surviving replicas — strictly a bonus).
``flipped``
    One atomic in-memory mutation per range (``flip_migration``):
    targets become the leading replicas, the source leaves the replica
    set, and its copies are scheduled for reconcile-delete. The flip is
    made DURABLE (a leadership-fenced synchronous placement flush)
    while the reconcile machinery is locked out (``_reconcile_serial``)
    — deletes can only run after the flip is in the znode, so a leader
    failover can never believe the source owns already-deleted copies.
    If the flush fails, the flip is rolled back (``unflip_migration``)
    before the lock is released. A flipped range is never re-flipped
    back: the phase rides the durable record.
``reconciled``
    The existing ``moved`` machinery (rejoin reconcile + periodic
    sweep, both crash-safe since PR 5) deletes the source's old copies;
    the migration record is dropped once the flip is durable because
    that machinery owns the tail from there.

Searches stay EXACT throughout: the per-request owner assignment makes
the flip atomic from the scatter path's perspective — before the flip
the source owns (target hits are dropped as non-owner), after it the
target owns (source hits are dropped, and additionally excluded via
the pending-reconcile set).

**Planning** detects overloaded shards (doc count above
``rebalance_max_shard_docs`` or above the cluster mean plus slack) and
underused capacity (a freshly joined worker sits far below the mean)
from the placement map the leader already maintains, and moves excess
ranges onto the least-loaded workers. **Drain** (``/api/drain``, CLI
``drain``) marks a worker as decommissioning — excluded from new-name
routing and repair targets — and migrates it empty so it can leave the
cluster with zero loss.
"""

from __future__ import annotations

import os
import threading
import time
from typing import TYPE_CHECKING

from tfidf_tpu.utils.faults import global_injector
from tfidf_tpu.utils.logging import get_logger
from tfidf_tpu.utils.metrics import global_metrics

if TYPE_CHECKING:   # circular at runtime: node.py constructs Rebalancer
    from tfidf_tpu.cluster.node import SearchNode

log = get_logger("cluster.rebalance")

# per-pass migration cap: bounds one sweep's wall time so the sweep
# loop's reconcile/repair duties are never starved by a huge rebalance
MAX_DOCS_PER_PASS = 256


def plan_moves(counts: dict[str, int], max_shard_docs: int
               ) -> dict[str, int]:
    """Pure planning: worker -> doc count in, ``{source: n_to_move}``
    out. A worker donates down to the cluster mean when it sits above
    ``mean + slack`` (slack = mean/4, at least 1) or above the absolute
    ``max_shard_docs`` cap (0 = no cap); receivers are workers below
    the mean, and total movement is bounded by their combined deficit —
    when every worker is loaded alike there is nowhere better to move
    to, and the plan is empty."""
    if len(counts) < 2:
        return {}
    total = sum(counts.values())
    if total <= 0:
        return {}
    mean = -(-total // len(counts))   # ceil
    slack = max(1, mean // 4)
    room = sum(max(0, mean - c) for c in counts.values())
    out: dict[str, int] = {}
    for w, c in sorted(counts.items(), key=lambda kv: (-kv[1], kv[0])):
        if room <= 0:
            break
        hi = mean + slack
        if max_shard_docs > 0:
            hi = min(hi, max_shard_docs)
        if c <= hi:
            continue
        n = min(c - mean, room)
        if n > 0:
            out[w] = n
            room -= n
    return out


class Rebalancer:
    """Leader-side rebalance/drain driver. Constructed on every node;
    does work only while this node is leader (like the reconcile
    sweep it rides on). All mutation goes through the staged migration
    machinery in :class:`~tfidf_tpu.cluster.placement.PlacementMap` and
    the node's existing resilience-wrapped RPC helpers."""

    def __init__(self, node: SearchNode) -> None:
        self.node = node
        # first automatic pass only after a full sweep period: a node
        # that JUST became leader should finish loading/repairing its
        # placement view before it starts planning moves against it
        self._last_run = time.monotonic()
        # one drain loop per worker; re-drain requests join the live one
        self._drain_threads: dict[str, threading.Thread] = {}
        self._drain_lock = threading.Lock()

    # ------------------------------------------------------------------
    # sweep integration
    # ------------------------------------------------------------------

    def maybe_run(self) -> None:
        """Called from the leader's reconcile sweep loop; self-paced by
        ``rebalance_sweep_ms`` (negative disables; the sweep interval
        itself is the floor)."""
        cfg = self.node.config
        if not cfg.rebalance_enabled or cfg.rebalance_sweep_ms < 0:
            return
        now = time.monotonic()
        if now - self._last_run < cfg.rebalance_sweep_ms / 1e3:
            return
        self._last_run = now
        self.run_once()

    def run_once(self) -> dict:
        """One planning + migration pass (public so tests and operators
        can force one without waiting for the timer)."""
        node = self.node
        if node._stopping or not node.config.rebalance_enabled \
                or not node.is_leader():
            return {}
        live = set(node.registry.get_all_service_addresses())
        if len(live) < 2:
            return {}
        self._abort_stale_migrations(live)
        draining = node.placement.draining_snapshot()
        counts = self._doc_counts(live)
        # draining workers neither donate here (their own drain loop
        # migrates them empty) nor receive
        plan = plan_moves({w: c for w, c in counts.items()
                           if w not in draining},
                          node.config.rebalance_max_shard_docs)
        moved = failed = 0
        budget = MAX_DOCS_PER_PASS
        for source, n in plan.items():
            if budget <= 0 or node._stopping:
                break
            names = self._movable_names(source, min(n, budget))
            if not names:
                continue
            out = self.migrate(source, names)
            moved += out.get("moved", 0)
            failed += out.get("failed", 0)
            budget -= len(names)
        if plan:
            log.info("rebalance pass", planned=sum(plan.values()),
                     moved=moved, failed=failed)
        return {"planned": sum(plan.values()), "moved": moved,
                "failed": failed}

    def _doc_counts(self, live: set[str]) -> dict[str, int]:
        counts = dict.fromkeys(live, 0)
        with self.node.placement.lock:
            for _name, ws in self.node.placement.replicas.items():
                for w in ws:
                    if w in counts:
                        counts[w] += 1
        return counts

    def _movable_names(self, source: str, n: int) -> list[str]:
        """Up to ``n`` docs held on ``source`` that are not already
        mid-migration or pending delete from it."""
        pm = self.node.placement
        skip = pm.migrating_names()
        skip |= pm.pending_moved().get(source, frozenset())
        return [name for name in sorted(pm.names_on(source))
                if name not in skip][:n]

    def _abort_stale_migrations(self, live: set[str]) -> None:
        """Drop copying-phase records whose source has left the cluster
        — the ordinary death path already re-owned its docs, and a flip
        against a vanished source is a no-op per name anyway."""
        pm = self.node.placement
        for mid, rec in pm.migration_snapshot().items():
            if rec["phase"] == "copying" and rec["source"] not in live:
                pm.end_migration(mid)
                log.info("aborted migration of departed source",
                         migration=mid, source=rec["source"])
        self._publish_active()

    def _publish_active(self) -> None:
        pm = self.node.placement
        with pm.lock:
            active = len(pm.migrations)
            draining = len(pm.draining)
        global_metrics.set_gauge("rebalance_active", active)
        global_metrics.set_gauge("rebalance_draining_workers", draining)

    # ------------------------------------------------------------------
    # the staged migration itself
    # ------------------------------------------------------------------

    def migrate(self, source: str, names: list[str],
                kind: str = "rebalance") -> dict:
        """Move ``names`` off ``source`` live: copy to chosen targets,
        durably flip ownership, then reconcile-delete the old copies.
        Serialized with the reconcile/repair machinery
        (``_reconcile_serial``) for the copy+flip stages so no delete
        or trim can interleave with a half-done flip; the reconcile
        trigger runs after the lock is released (the sweep retries any
        failure — the moved state is already durable by then)."""
        node = self.node
        out = {"moved": 0, "failed": 0}
        if not names:
            return out
        flipped: list[str] = []
        with node._reconcile_serial:
            if node._stopping or not node.is_leader():
                return out
            targets_by_name = self._choose_targets(source, names)
            if not targets_by_name:
                return out
            mid = node.placement.begin_migration(source, targets_by_name,
                                                 kind)
            self._publish_active()
            try:
                global_injector.check("leader.rebalance_copy")
                # copy phase: the same resilience-wrapped byte-sourcing
                # + upload fan-out as anti-entropy repair (confirmed
                # legs are recorded as replicas by the shared helper)
                node._replicate_to_targets(targets_by_name)
                global_injector.check("leader.rebalance_flip")
                flipped = node.placement.flip_migration(mid)
                if flipped and not self._persist_flip():
                    # the flip could not be made durable: roll it back
                    # BEFORE any delete can run — a non-durable flip
                    # followed by deletes would let a leader failover
                    # resurrect source ownership of deleted copies
                    node.placement.unflip_migration(mid)
                    flipped = []
                if flipped:
                    # a flip changes which shard SCORES each moved doc
                    # (per-shard df shifts with ownership): cached
                    # query results predate it and must die
                    node.bump_result_generation()
                out["moved"] = len(flipped)
                out["failed"] = len(targets_by_name) - len(flipped)
            except Exception as e:
                out["failed"] = len(targets_by_name) - len(flipped)
                log.warning("migration failed", source=source,
                            docs=len(targets_by_name), err=repr(e))
            finally:
                # the record's job ends here either way: a durable flip
                # hands the tail to the moved machinery; an abort leaves
                # confirmed copy legs as plain over-replication for the
                # trim pass to reclaim
                node.placement.end_migration(mid)
                self._publish_active()
        if out["moved"]:
            global_metrics.inc("rebalance_moved_docs", out["moved"])
            log.info("migration flipped", source=source,
                     docs=out["moved"], kind=kind)
        if out["failed"]:
            global_metrics.inc("rebalance_failures", out["failed"])
        if out["moved"]:
            # reconcile phase: trigger the source-side deletes now
            # instead of waiting a sweep period; any failure (including
            # an injected one) is retried by the periodic sweep — the
            # moved state is durable
            try:
                global_injector.check("leader.rebalance_reconcile")
                node.run_reconcile_sweep()
            except Exception as e:
                log.warning("post-flip reconcile trigger failed "
                            "(sweep will retry)", err=repr(e))
        return out

    def _choose_targets(self, source: str,
                        names: list[str]) -> dict[str, list[str]]:
        """Per-name target selection: the least-loaded live, non-source,
        non-draining, breaker-closed worker not already holding the
        name. Names with no viable target are dropped from the
        migration (left where they are)."""
        node = self.node
        live = set(node.registry.get_all_service_addresses())
        draining = node.placement.draining_snapshot()
        pool = [w for w in live
                if w != source and w not in draining
                and not node.resilience.board.is_open(w)]
        if not pool:
            return {}
        try:
            node._ensure_sizes_fresh(pool)
        except Exception as e:
            log.warning("rebalance size poll failed", err=repr(e))
            return {}
        with node._placement_lock:
            sizes = {w: s for w, s in node._size_cache[1].items()
                     if w in pool}
        if not sizes:
            return {}
        out: dict[str, list[str]] = {}
        for name in names:
            reps = node.placement.holders_of(name)
            if source not in reps:
                continue
            cands = sorted((w for w in sizes if w not in reps),
                           key=lambda w: (sizes[w], w))
            if not cands:
                continue
            target = cands[0]
            # grow the local estimate by the doc's projected bytes (the
            # size cache is byte-denominated) so one pass spreads its
            # own load across targets instead of stacking every doc
            # onto the single smallest worker
            sizes[target] += self._est_doc_bytes(name)
            out[name] = [target]
        return out

    def _est_doc_bytes(self, name: str) -> int:
        """Projected on-target size of one doc: the durable-store file
        size when known, else a nominal document size."""
        try:
            return max(1, os.path.getsize(self.node._store_path(name)))
        except Exception:
            return 4096

    def _persist_flip(self) -> bool:
        """Make the flip durable (leadership-fenced inside ``flush``).
        With persistence disabled by config the in-memory map IS the
        authority (per-tenure mode) and the flip stands."""
        node = self.node
        if node.config.placement_flush_ms < 0:
            return True
        try:
            return node.placement.flush()
        except Exception as e:
            log.warning("flip persist failed; rolling back", err=repr(e))
            return False

    # ------------------------------------------------------------------
    # new-leader resume
    # ------------------------------------------------------------------

    def resume_after_election(self) -> dict:
        """Resolve a predecessor's in-flight migrations after the
        durable map loaded: copying-phase records are ABORTED (ownership
        never moved; confirmed copy legs are over-replication the trim
        pass reclaims), flipped records are DROPPED (the flip is
        durable and the loaded ``moved`` state already carries the
        reconcile tail through the sweep), and drain loops restart for
        workers still marked draining."""
        node = self.node
        aborted = resumed = 0
        for mid, rec in node.placement.migration_snapshot().items():
            if rec["phase"] == "copying":
                aborted += 1
            else:
                resumed += 1
            node.placement.end_migration(mid)
        drains = 0
        for w in node.placement.draining_snapshot():
            self._ensure_drain_thread(w)
            drains += 1
        self._publish_active()
        if aborted or resumed or drains:
            log.info("resumed rebalance state after election",
                     aborted_copying=aborted, flipped_resumed=resumed,
                     drains_restarted=drains)
        return {"aborted": aborted, "resumed": resumed, "drains": drains}

    # ------------------------------------------------------------------
    # drain (planned decommission)
    # ------------------------------------------------------------------

    def start_drain(self, worker: str) -> dict:
        """Mark ``worker`` as decommissioning and start migrating it
        empty. Idempotent: a repeated request reports the in-progress
        drain. The draining flag rides the durable placement znode, so
        a leader failover restarts the drain instead of forgetting it."""
        changed = self.node.placement.set_draining(worker, True)
        if changed:
            global_metrics.inc("rebalance_drains_started")
            try:   # make the flag durable promptly (best-effort; the
                self.node.placement.flush()   # dirty flush covers it)
            except Exception:
                pass
        self._ensure_drain_thread(worker)
        self._publish_active()
        return self.drain_status(worker)

    def cancel_drain(self, worker: str) -> dict:
        """Clear the draining flag; the drain loop exits on its next
        check and already-moved docs stay where they landed."""
        self.node.placement.set_draining(worker, False)
        self._publish_active()
        return self.drain_status(worker)

    def drain_status(self, worker: str) -> dict:
        pm = self.node.placement
        remaining = len(pm.names_on(worker))
        pending = len(pm.pending_moved().get(worker, frozenset()))
        return {"worker": worker,
                "draining": worker in pm.draining_snapshot(),
                "remaining": remaining,
                "pending_delete": pending,
                "drained": remaining == 0 and pending == 0}

    def _ensure_drain_thread(self, worker: str) -> None:
        with self._drain_lock:
            t = self._drain_threads.get(worker)
            if t is not None and t.is_alive():
                return
            t = threading.Thread(
                target=self._drain_loop, args=(worker,), daemon=True,
                name=f"drain-{self.node.config.port}")
            self._drain_threads[worker] = t
            t.start()

    def _drain_loop(self, worker: str) -> None:
        node = self.node
        stalls = 0
        # count a completion only when THIS loop saw work to do: a
        # restarted loop over an already-empty draining worker (leader
        # failover, repeated POST) must not re-increment the lifetime
        # counter on its first empty check
        progressed = False
        while not node._stopping:
            if not node.is_leader() \
                    or worker not in node.placement.draining_snapshot():
                return
            pending = node.placement.pending_moved().get(
                worker, frozenset())
            names = [n for n in sorted(node.placement.names_on(worker))
                     if n not in pending][:MAX_DOCS_PER_PASS]
            if names or pending:
                progressed = True
            if not names:
                if not pending:
                    if progressed:
                        global_metrics.inc("rebalance_drains_completed")
                    log.info("drain complete; worker holds no placed "
                             "documents", worker=worker)
                    return
                time.sleep(0.2)   # deletes still landing via the sweep
                continue
            out = self.migrate(worker, names, kind="drain")
            if out.get("moved", 0) == 0:
                # no progress (no capacity — e.g. every live worker
                # already holds these docs — faults, or not leader):
                # back off and retry; the drain never degrades the
                # replication factor, so it WAITS for capacity (a new
                # worker joining) instead of dropping copies. Stay
                # loud: a stalled drain is an operator-visible state.
                stalls += 1
                if stalls % 20 == 1:
                    log.warning(
                        "drain stalled: no viable migration target "
                        "for remaining docs (needs a live, "
                        "non-draining worker not already holding "
                        "them); will keep retrying",
                        worker=worker, remaining=len(names))
                time.sleep(0.5)
            else:
                stalls = 0

"""Poison-query quarantine — the leader/router's memory of queries
that kill devices (ISSUE 20).

A poisoned output (NaN rows detected at the fetch seam) is a property
of the (query, plan) pair meeting a kernel bug or pathological shape —
NOT of the worker that happened to score it. Retrying or failing over
such a query marches it through the replica set, taking a device down
at every stop (the classic query-of-death cascade). The quarantine
breaks that march: after compute faults on ``poison_quarantine_after``
DISTINCT replicas (one replica could just be a sick device; two
independent devices agreeing indicts the query), the fingerprint is
quarantined and the router answers 422 + ``X-Poison-Quarantined``
without touching any worker.

Wire fingerprint: the worker stamps the offending queries' fingerprints
in ``X-Poison-Fingerprints`` (computed next to the detection), the
router blames per-worker and checks admission per-query with the SAME
function — so worker and router can never disagree on identity.

Entries expire (TTL) — a rolled binary or fixed kernel deserves a
retry — and the table is a bounded LRU, so a hostile query stream
cannot grow it without bound. ``resilience.classify_compute_fault``
guarantees poison is never folded into network-fault accounting.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict

from tfidf_tpu.utils.logging import get_logger
from tfidf_tpu.utils.metrics import global_metrics
from tfidf_tpu.utils.tracing import span_event

log = get_logger("cluster.quarantine")


def poison_fingerprint(query: str, mode: str = "sparse") -> str:
    """Stable 12-hex fingerprint of a (query, plan) pair. ``mode`` is
    the serving plan (sparse | dense | hybrid) — the same text can be
    fine on one plane and poisonous on another, so the plan is part of
    the identity."""
    h = hashlib.sha1(f"{mode}\x00{query}".encode("utf-8", "replace"))
    return h.hexdigest()[:12]


class _Entry:
    __slots__ = ("workers", "quarantined_at", "touched_at")

    def __init__(self, now: float) -> None:
        self.workers: set[str] = set()
        self.quarantined_at: float | None = None
        self.touched_at = now


class PoisonQuarantine:
    """Bounded, TTL'd LRU of poison-fingerprint verdicts.

    Thread-safe: the router's merge loop blames from scatter worker
    threads while admission checks run on request threads.
    """

    def __init__(self, *, after: int = 2, ttl_s: float = 300.0,
                 max_entries: int = 256,
                 clock=time.monotonic) -> None:
        self.after = max(1, int(after))
        self.ttl_s = float(ttl_s)
        self.max_entries = max(1, int(max_entries))
        self._clock = clock
        self._lock = threading.Lock()
        self._entries: OrderedDict[str, _Entry] = OrderedDict()

    # ---- internal ----

    def _get(self, fp: str, now: float) -> _Entry:
        e = self._entries.get(fp)
        if e is not None and now - e.touched_at > self.ttl_s:
            del self._entries[fp]
            e = None
        if e is None:
            e = _Entry(now)
            self._entries[fp] = e
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)   # evict LRU
        else:
            self._entries.move_to_end(fp)
            e.touched_at = now
        return e

    # ---- writer: per-worker blame from the scatter merge ----

    def note_fault(self, fp: str, worker: str) -> bool:
        """Record a compute fault for ``fp`` observed on ``worker``.
        Returns True when this observation CROSSES the replica-distinct
        threshold (the quarantine moment — log/trace once, not per
        subsequent hit)."""
        now = self._clock()
        with self._lock:
            e = self._get(fp, now)
            e.workers.add(worker)
            if (e.quarantined_at is None
                    and len(e.workers) >= self.after):
                e.quarantined_at = now
                global_metrics.inc("poison_quarantined")
                span_event("poison.quarantined", fingerprint=fp,
                           replicas=len(e.workers))
                log.warning("poison query quarantined",
                            fingerprint=fp, replicas=len(e.workers))
                return True
        return False

    # ---- reader: admission ----

    def is_quarantined(self, fp: str) -> bool:
        now = self._clock()
        with self._lock:
            e = self._entries.get(fp)
            if e is None or e.quarantined_at is None:
                return False
            if now - e.touched_at > self.ttl_s:
                del self._entries[fp]
                return False
            # a hit keeps the verdict warm: an actively re-sent poison
            # query must not slip back in just by persisting past TTL/2
            e.touched_at = now
            self._entries.move_to_end(fp)
            return True

    # ---- ops surface (/api/quarantine, CLI inspect/clear) ----

    def snapshot(self) -> dict:
        now = self._clock()
        with self._lock:
            live = {fp: e for fp, e in self._entries.items()
                    if now - e.touched_at <= self.ttl_s}
            return {
                "after": self.after,
                "ttl_s": self.ttl_s,
                "max_entries": self.max_entries,
                "tracked": len(live),
                "quarantined": [
                    {"fingerprint": fp,
                     "replicas": sorted(e.workers),
                     "age_s": round(now - (e.quarantined_at or now), 3)}
                    for fp, e in live.items()
                    if e.quarantined_at is not None],
            }

    def clear(self) -> int:
        """Drop every entry (operator override after a fix rolls out);
        returns how many were quarantined."""
        with self._lock:
            n = sum(1 for e in self._entries.values()
                    if e.quarantined_at is not None)
            self._entries.clear()
        if n:
            log.info("poison quarantine cleared", dropped=n)
        return n

"""Wire-protocol versioning — explicit version negotiation per RPC.

A fleet under rolling upgrade is never all one version: for the window
where old and new binaries coexist, every peer must either understand
the other's wire surface or refuse it HONESTLY. The reference gets
this for free from a stateless binary behind a k8s Deployment; our
stateful workers, fenced leader, and durable placement map make mixed-
version operation a real correctness problem (ROADMAP open item 4).
The discipline mirrors leadership fencing (cluster/fencing.py):

- every outbound RPC at the shared HTTP seams (``http_get`` /
  ``http_post`` / ``_ScatterClient.post`` / ``http_get_stream`` in
  cluster/node.py) stamps ``X-Proto-Version`` with the sender's wire
  version, beside ``X-Leader-Epoch`` where that rides;
- every front-plane reply stamps ``X-Proto-Version`` with the
  server's version (``_HttpHandlerBase._send``), so either side of
  any exchange can detect skew;
- handlers on the data planes (``/leader/*``, ``/worker/*``) accept a
  declared compat window ``[proto_min_compat, +inf)``: a request whose
  declared version is BELOW the floor is answered with the distinct
  status ``426 Upgrade Required`` + ``X-Proto-Rejected: 1`` —
  non-retryable and never a worker fault (a version cannot come back
  by retrying), so it never trips breakers
  (:func:`cluster.resilience.is_proto_rejection`);
- a request with NO version header is implicitly version 1 — the
  pre-versioning wire every binary before this module spoke. With the
  default floor of 1, old binaries interoperate unchanged; an operator
  raises the floor only after the whole fleet has upgraded past it;
- versions NEWER than ours are accepted (forward compatibility: a
  newer peer only ever ADDS surface, and unknown headers pass
  through untouched — pinned in tests/test_upgrade.py). Rejection is
  one-sided: only the floor rejects.

Ops endpoints (``/api/*``, metrics, trace export) are deliberately
version-agnostic: an operator must be able to inspect a node whatever
binary it runs — exactly the reads-unfenced choice fencing made.

The version itself is part of the machine-checked wire contract:
graftcheck's protocol pass (tools/graftcheck/protocol.py) reads
``PROTO_VERSION`` from this module, cross-checks it against the README
contract table's declared version and the pinned contract fingerprint,
and flags any wire-surface change that lands without a version bump.

Version history (bump PROTO_VERSION when the wire surface changes in
a way an old peer could misread; update the README fingerprint and the
``since``/``until`` columns in the same commit):

  1  the implicit pre-versioning wire (PRs 1-15): no version header.
  2  this module: X-Proto-Version / X-Proto-Rejected, 426 rejections,
     capture/replay request log, /api/health proto_version field.
  3  hybrid retrieval: additive ``mode`` (sparse|dense|hybrid) and
     ``fusion`` (rrf|wsum) fields on /leader/start and ``mode`` on
     /worker/process-batch (all slice re-issues too); staged replies
     carry 2n hit lists (n sparse then n dense) on the v2 packed
     wire; /leader/start replies stamp X-Search-Stages; /api/health
     gains the ``embedding`` block. Absent fields mean sparse — a
     v2 request is byte-for-byte a valid v3 sparse request, and a
     v2 worker that ignores ``mode`` replies n lists, which the
     leader's slot-count check catches (honest degradation, never a
     silently sparse-only "hybrid" result).
  4  compute-plane chaos (ISSUE 20): additive reply headers only.
     Workers stamp X-Compute-Degraded on 2xx replies served from the
     host mirror and X-Compute-Fault (+ X-Poison-Fingerprints for
     poison) on compute-fault 500s; the read plane answers 422 +
     X-Poison-Quarantined for quarantined queries and relays
     X-Compute-Degraded on merged replies. A v3 peer ignoring every
     new header sees the v3 wire unchanged (extra headers on replies
     it already handles; the 422 is the application-rejection class
     v3 clients already never retry).
"""

from __future__ import annotations

# the current wire-protocol version this binary speaks (see history
# table above — bump beside any wire-surface change)
PROTO_VERSION = 4

# the wire contract (stamped/checked at the shared HTTP seams)
PROTO_HEADER = "X-Proto-Version"
PROTO_REJECTED_HEADER = "X-Proto-Rejected"
PROTO_STATUS = 426          # Upgrade Required: distinct, non-retryable

# the version implicitly declared by a request with no version header:
# every binary that predates this module
IMPLICIT_VERSION = 1


def proto_headers() -> dict:
    """The outbound stamp every RPC carries (beside the fence epoch
    where that rides)."""
    return {PROTO_HEADER: str(PROTO_VERSION)}


def parse_version(value) -> int:
    """The wire version a request declares. ``value`` is the raw
    ``X-Proto-Version`` header (or None). Absent or malformed headers
    are the implicit pre-versioning wire — permissive by construction,
    like a malformed trace id: garbage never escalates to a rejection
    the sender cannot act on."""
    if value is None:
        return IMPLICIT_VERSION
    try:
        v = int(str(value).strip())
    except ValueError:
        return IMPLICIT_VERSION
    return v if v >= 1 else IMPLICIT_VERSION


def in_window(peer_version: int, min_compat: int) -> bool:
    """The compat-window rule: accept any peer at or above the floor.
    There is deliberately no ceiling — a newer peer is always accepted
    (forward compatibility; unknown headers pass through), so a rolling
    upgrade can proceed in either direction one process at a time."""
    return peer_version >= min_compat

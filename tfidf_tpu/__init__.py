"""tfidf_tpu — a TPU-native distributed full-text search framework.

A brand-new JAX/XLA/Pallas framework with the capabilities of the reference
system kheder-hassoun/Tf-IDF-Distributed-System (a Spring Boot + ZooKeeper +
Lucene distributed TF-IDF search engine, see /root/reference): document
ingest with idempotent upsert, sharded indexing, scatter-gather query
scoring, load-balanced uploads, membership/liveness, leader failover,
checkpoint/resume — re-designed TPU-first:

- the per-worker Lucene index (reference ``worker/Worker.java:54-94``)
  becomes a CSR term-document matrix resident on TPU devices;
- query scoring (``Worker.java:222-241``) becomes a batched sparse-dense
  contraction with exact top-k on the MXU/VPU;
- the leader's scatter-gather + score merge (``leader/Leader.java:39-92``)
  becomes ``shard_map`` collectives (``psum`` for global document frequency
  and score reduction, ``all_gather`` for distributed top-k) over a
  ``jax.sharding.Mesh``;
- ZooKeeper election/registry (``leader/LeaderElection.java``,
  ``registry/ServiceRegistry.java``) becomes a small coordination service
  with the same znode semantics (ephemeral-sequential nodes, one-shot
  watches) driving an HTTP control plane.

Subpackages:
    ops       pure-JAX/Pallas compute: analyzer, CSR, scoring, top-k
    models    scoring model families: TF-IDF variants, Lucene-parity BM25
    parallel  mesh construction + sharded scoring collectives
    engine    host-side index: vocabulary, segments, checkpoints, searcher
    cluster   control plane: coordination, election, registry, HTTP nodes
    utils     config, structured logging, metrics, tracing, fault injection
"""

__version__ = "0.1.0"

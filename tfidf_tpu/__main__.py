import sys

from tfidf_tpu.cli import main

sys.exit(main())

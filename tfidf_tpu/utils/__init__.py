from tfidf_tpu.utils.config import Config, load_config
from tfidf_tpu.utils.logging import get_logger
from tfidf_tpu.utils.metrics import Metrics, global_metrics
from tfidf_tpu.utils.tracing import trace_phase, phase_timings
from tfidf_tpu.utils.faults import FaultInjector, fault_point

__all__ = [
    "Config",
    "load_config",
    "get_logger",
    "Metrics",
    "global_metrics",
    "trace_phase",
    "phase_timings",
    "FaultInjector",
    "fault_point",
]

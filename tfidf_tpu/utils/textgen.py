"""Offline realistic-text corpus generator (VERDICT r3 #3).

Every earlier bench corpus was synthetic ``t{i}`` integer tokens, which
bypasses the analyzer's real work (Unicode rules, punctuation, the
native ASCII fast path / Python fallback boundary, the extractors). The
reference's workload is real text files run through Lucene's
``StandardAnalyzer`` + Tika (``Worker.java:190-220``). This module
builds a realistic corpus **without network egress**:

* **Lexicon**: real English words harvested from text already in the
  image (Python stdlib sources' docstrings/comments and
  ``/usr/share/doc``), frequency-ranked so a Zipf draw over ranks
  reproduces natural-language token statistics over *actual word
  forms*.
* **Documents**: sentence-cased word sequences with commas/periods,
  apostrophe forms (``word's``, ``don't``-style contractions), numeric
  tokens, paragraph breaks; a configurable fraction are HTML-wrapped,
  Latin-1-encoded (non-UTF-8 charset-fallback path), or binary garbage
  that the ingest contract must refuse with ``UnsupportedMediaType``
  (the 415 path, ``ops/analyzer.py``).
"""

from __future__ import annotations

import collections
import glob
import os
import re
import sysconfig

import numpy as np

_WORD_RE = re.compile(rb"[a-z][a-z]{1,13}")

# fallback seed vocabulary if the image has no harvestable text at all
_SEED = ("the of and to in a is that for it as was with be by on not he "
         "this are or his from at which but have an had they you were "
         "her all she there would their we him been has when who will "
         "more no if out so said what up its about into than them can "
         "only other new some could time these two may then do first "
         "any my now such like our over man me even most made after "
         "also did many before must through years where much your way "
         "well down should because each just those people how too "
         "little state good very make world still own see men work "
         "long get here between both life being under never day same "
         "another know while last might us great old year off come "
         "since against go came right used take three").split()


def harvest_lexicon(max_words: int = 30_000,
                    max_bytes: int = 64 << 20) -> tuple[list[str],
                                                        np.ndarray]:
    """Frequency-ranked English lexicon from text already on disk.

    Returns ``(words, counts)`` sorted by descending frequency. Sources:
    Python stdlib ``.py`` files (docstrings + comments are mostly
    English prose) and ``/usr/share/doc`` README/changelog text.
    Deterministic for a fixed filesystem."""
    counter: collections.Counter[bytes] = collections.Counter()
    budget = max_bytes
    sources: list[str] = []
    stdlib = sysconfig.get_paths().get("stdlib")
    if stdlib and os.path.isdir(stdlib):
        sources.extend(sorted(glob.glob(os.path.join(stdlib, "*.py"))))
        sources.extend(sorted(glob.glob(
            os.path.join(stdlib, "*", "*.py")))[:500])
    for root in ("/usr/share/doc",):
        if os.path.isdir(root):
            for dirpath, _dirs, files in sorted(os.walk(root)):
                for f in sorted(files):
                    if f.endswith((".txt", ".md", "README", "copyright",
                                   "README.Debian")):
                        sources.append(os.path.join(dirpath, f))
    for path in sources:
        if budget <= 0:
            break
        try:
            with open(path, "rb") as f:
                data = f.read(min(budget, 1 << 20))
        except OSError:
            continue
        budget -= len(data)
        counter.update(_WORD_RE.findall(data.lower()))
    if len(counter) < 200:   # pathological image: fall back to the seed
        counter.update({w.encode(): 1000 - i
                        for i, w in enumerate(_SEED)})
    ranked = counter.most_common(max_words)
    words = [w.decode() for w, _ in ranked]
    counts = np.asarray([c for _, c in ranked], np.float64)
    return words, counts


_CONTRACTIONS = ("n't", "'s", "'ll", "'re", "'ve", "'d")


class RealisticCorpus:
    """Deterministic generator of realistic document byte-payloads."""

    def __init__(self, rng, words: list[str] | None = None,
                 zipf_a: float = 1.15) -> None:
        self.rng = rng
        if words is None:
            words, _ = harvest_lexicon()
        self.words = words
        ranks = np.arange(1, len(words) + 1, dtype=np.float64)
        p = ranks ** (-zipf_a)
        self.p = p / p.sum()

    def _sample_words(self, n: int) -> list[str]:
        idx = self.rng.choice(len(self.words), size=n, p=self.p)
        return [self.words[i] for i in idx]

    def make_text(self, avg_len: int) -> str:
        """One plain-text document: sentences with casing, punctuation,
        contractions, numbers, paragraph breaks."""
        rng = self.rng
        n = max(8, int(rng.poisson(avg_len)))
        toks = self._sample_words(n)
        out: list[str] = []
        sent_pos = 0
        for i, w in enumerate(toks):
            r = rng.random()
            if r < 0.03:
                w = w + _CONTRACTIONS[int(rng.integers(
                    0, len(_CONTRACTIONS)))]
            elif r < 0.08:
                w = str(int(rng.integers(0, 100000)))
            if sent_pos == 0:
                w = w.capitalize()
            sent_pos += 1
            end = sent_pos >= int(rng.integers(5, 18)) or i == n - 1
            if end:
                w += "."
                sent_pos = 0
                if rng.random() < 0.15:
                    w += "\n\n"
            elif rng.random() < 0.08:
                w += ","
            out.append(w)
        return " ".join(out)

    def make_payload(self, avg_len: int, *, html_frac: float = 0.03,
                     latin1_frac: float = 0.02,
                     binary_frac: float = 0.005
                     ) -> tuple[bytes, str]:
        """One document as raw upload bytes.

        Returns ``(payload, kind)`` with kind in ``plain`` / ``html`` /
        ``latin1`` / ``binary``; ``binary`` payloads must be refused by
        the ingest contract (415)."""
        r = self.rng.random()
        if r < binary_frac:
            # realistic stray binaries: recognized magic + random bytes
            # (a PNG, a JPEG, an ELF, a gzip — what actually lands in a
            # documents folder by accident). These must 415.
            magics = (b"\x89PNG\r\n\x1a\n", b"\xff\xd8\xff\xe0",
                      b"\x7fELF", b"\x1f\x8b\x08")
            magic = magics[int(self.rng.integers(0, len(magics)))]
            blob = self.rng.integers(0, 256, size=512,
                                     dtype=np.uint8).tobytes()
            return magic + blob, "binary"
        text = self.make_text(avg_len)
        if r < binary_frac + html_frac:
            body = text.replace("\n\n", "</p><p>")
            return (f"<html><head><title>doc</title>"
                    f"<style>p{{margin:0}}</style></head>"
                    f"<body><p>{body}</p></body></html>"
                    ).encode(), "html"
        if r < binary_frac + html_frac + latin1_frac:
            # sprinkle Latin-1-only characters so the payload is NOT
            # valid UTF-8 and must ride the charset fallback
            text = text.replace(" the ", " caf\xe9 ", 1)
            if "\xe9" not in text:
                text = "caf\xe9 " + text
            return text.encode("latin-1"), "latin1"
        return text.encode(), "plain"

"""Structured logging.

The reference logs narratively on every path via SLF4J/Logback
(``logback.xml:27-29``; e.g. ``Leader.java:41-90``, ``Worker.java:59-89``).
Here we emit single-line structured records (human prefix + key=value tail)
so the same stream doubles as a machine-parseable event log.

Records emitted while a trace span is active (``utils/tracing.py``)
carry a ``trace=<trace id>`` field, so slow-query log lines and every
warn/error on a traced request path are joinable with ``GET
/api/trace/<id>`` output. The trace id is read off a contextvar at
RECORD CREATION time (``_KVAdapter.process`` runs on the emitting
thread), not at formatting time — handlers may format on another
thread where the contextvar would be empty.
"""

from __future__ import annotations

import logging
import os
import sys
import threading
import time

_CONFIGURED = False
_LOCK = threading.Lock()


def _trace_id() -> str | None:
    # late import: logging is imported by nearly everything, including
    # modules tracing itself depends on at import time
    from tfidf_tpu.utils.tracing import current_trace_id
    return current_trace_id()


class _KVFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        base = super().format(record)
        kv = getattr(record, "kv", None)
        if kv:
            tail = " ".join(f"{k}={v}" for k, v in sorted(kv.items()))
            return f"{base} | {tail}"
        return base


class _KVAdapter(logging.LoggerAdapter):
    """Lets call sites pass arbitrary keyword fields: log.info("msg", docs=3)."""

    _RESERVED = {"exc_info", "stack_info", "stacklevel", "extra"}

    def process(self, msg, kwargs):
        kv = {k: v for k, v in kwargs.items() if k not in self._RESERVED}
        tid = _trace_id()
        if tid is not None and "trace" not in kv:
            kv["trace"] = tid
        passthrough = {k: v for k, v in kwargs.items() if k in self._RESERVED}
        passthrough.setdefault("extra", {})["kv"] = kv
        return msg, passthrough


def _configure() -> None:
    global _CONFIGURED
    with _LOCK:
        if _CONFIGURED:
            return
        root = logging.getLogger("tfidf_tpu")
        level = os.environ.get("TFIDF_LOG_LEVEL", "INFO").upper()
        root.setLevel(level)
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(_KVFormatter(
            "%(asctime)s %(levelname)s %(name)s: %(message)s", "%H:%M:%S"))
        root.addHandler(handler)
        root.propagate = False
        _CONFIGURED = True


def get_logger(name: str) -> _KVAdapter:
    _configure()
    return _KVAdapter(logging.getLogger(f"tfidf_tpu.{name}"), {})


class Stopwatch:
    """Tiny timing helper for log lines: with Stopwatch() as sw: ...; sw.ms"""

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.seconds = time.perf_counter() - self.t0
        self.ms = round(self.seconds * 1e3, 2)
        return False

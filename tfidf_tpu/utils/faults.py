"""Fault injection.

The reference tolerates faults (ZooKeeper ephemeral-node liveness, partial
scatter-gather, ``Leader.java:67-69``) but has no way to *inject* them
(SURVEY.md §5.3: "Fault injection: none"). This module adds that capability:
named fault points are sprinkled through the control plane (worker RPC,
heartbeat, checkpoint write) and a test/chaos harness can arm them to raise,
delay, or drop with a given probability.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass


class FaultInjected(RuntimeError):
    pass


@dataclass
class _Rule:
    action: str            # "raise" | "delay" | "callable"
    probability: float = 1.0
    delay_s: float = 0.0
    remaining: int | None = None   # fire at most N times; None = unlimited
    fn: object = None


class FaultInjector:
    def __init__(self, seed: int | None = None) -> None:
        self._rules: dict[str, _Rule] = {}
        self._lock = threading.Lock()
        self._rng = random.Random(seed)
        self.fired: dict[str, int] = {}

    def arm(self, point: str, action: str = "raise", probability: float = 1.0,
            delay_s: float = 0.0, times: int | None = None,
            fn=None) -> None:
        with self._lock:
            self._rules[point] = _Rule(action, probability, delay_s, times, fn)

    def disarm(self, point: str | None = None) -> None:
        with self._lock:
            if point is None:
                self._rules.clear()
            else:
                self._rules.pop(point, None)

    def check(self, point: str) -> None:
        with self._lock:
            rule = self._rules.get(point)
            if rule is None:
                return
            if rule.remaining is not None:
                if rule.remaining <= 0:
                    return
            if self._rng.random() > rule.probability:
                return
            if rule.remaining is not None:
                rule.remaining -= 1
            self.fired[point] = self.fired.get(point, 0) + 1
            action, delay_s, fn = rule.action, rule.delay_s, rule.fn
        if action == "delay":
            time.sleep(delay_s)
        elif action == "callable" and fn is not None:
            fn()
        elif action == "raise":
            raise FaultInjected(f"fault injected at {point!r}")


# Process-wide injector used by library fault points; tests arm/disarm it.
global_injector = FaultInjector()


def fault_point(name: str) -> None:
    """Call at a named site; no-op unless a test armed this point."""
    global_injector.check(name)

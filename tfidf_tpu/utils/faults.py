"""Fault injection.

The reference tolerates faults (ZooKeeper ephemeral-node liveness, partial
scatter-gather, ``Leader.java:67-69``) but has no way to *inject* them
(SURVEY.md §5.3: "Fault injection: none"). This module adds that capability:
named fault points are sprinkled through the control plane (worker RPC,
heartbeat, checkpoint write) and a test/chaos harness can arm them to raise,
delay, or drop with a given probability.

Every fault point in the tree is declared in :data:`KNOWN_FAULT_POINTS`
(``tfidf_tpu faults list`` prints it) so chaos configs can be validated
against the code instead of silently going stale; a tier-1 test greps the
sources and fails if a ``check()`` site is missing from the registry.
Arming a name ending in ``*`` matches any point with that prefix (e.g.
``coord.heartbeat.*`` covers the per-session server-side heartbeat
points) — fires are counted under the wildcard rule's name.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass

from tfidf_tpu.utils.tracing import span_event

# Registry of every fault point compiled into the tree: name -> where it
# fires. Dynamic per-instance points are declared with a ``*`` suffix.
KNOWN_FAULT_POINTS: dict[str, str] = {
    "leader.worker_rpc": "leader scatter RPC to one worker "
                         "(per-query and batched paths)",
    "leader.size_poll": "leader polling one worker's /worker/index-size",
    "leader.reconcile_rpc": "leader's /worker/delete rejoin-reconcile RPC",
    "leader.sweep": "one reconciliation-sweep pass on the leader",
    "leader.replica_rpc": "leader re-issuing an orphaned ownership slice "
                          "to a surviving replica (failover scatter read)",
    "leader.hedge": "leader dispatching a hedged duplicate read for a "
                    "laggard worker's ownership slice",
    "leader.repair": "one anti-entropy replication-repair pass on the "
                     "leader (restore R / trim over-replication)",
    "leader.placement_persist": "leader persisting the placement map to "
                                "the coordination substrate",
    "leader.rebalance_copy": "rebalancer about to copy a migrating doc "
                             "range to its targets (pre-copy crash "
                             "window: ownership has not moved)",
    "leader.rebalance_flip": "rebalancer about to atomically flip "
                             "ownership of a copied range (pre-flip "
                             "crash window: the copy legs are plain "
                             "over-replication)",
    "leader.rebalance_reconcile": "rebalancer about to trigger the "
                                  "reconcile deletes after a durable "
                                  "flip (failure retried by the sweep)",
    "leader.admission": "front-door admission decision for one "
                        "/leader/* request (arm to chaos-test the "
                        "shed path itself)",
    "leader.autopilot": "one SLO-autopilot control pass on the leader "
                        "(arm to chaos-test the sweep loop's tolerance "
                        "of a failing controller)",
    "worker.process": "worker handling /worker/process[-batch]",
    "worker.upload": "worker handling /worker/upload[-batch]",
    "worker.fence": "worker checking a mutating RPC's X-Leader-Epoch "
                    "against its durable fence (arm to chaos-test the "
                    "fence path itself)",
    "router.view_refresh": "a placement follower view (router / "
                           "any-node read plane) about to re-arm its "
                           "watch and re-read the placement znode",
    "router.write_proxy": "a router (or non-leader node) about to "
                          "forward a front-door mutation to the "
                          "elected leader",
    "coord.heartbeat.*": "coordination server receiving a session "
                         "heartbeat (suffix: session id)",
    "coord.heartbeat_send": "coordination client sending a heartbeat",
    "coord.long_poll": "coordination client's event long-poll",
    "resilience.backoff": "retry policy about to sleep a backoff delay",
    "resilience.breaker_trip": "circuit breaker transitioning to open "
                               "(observe-only: armed raise is swallowed)",
    "resilience.breaker_probe": "circuit breaker admitting a half-open "
                                "probe (observe-only)",
    "checkpoint.pre_publish": "checkpoint written but not yet published "
                              "(crash window)",
    "storage.write": "durable-IO seam about to write a file's bytes "
                     "(utils/storage.py; torn-write / ENOSPC window)",
    "storage.fsync": "durable-IO seam about to fsync a file or "
                     "directory (the fsync-EIO window)",
    "storage.read": "durable-IO seam reading a durable file back "
                    "(the bit-rot window — damage here is silent "
                    "unless a checksum catches it)",
    "storage.rename": "durable-IO seam about to atomically publish "
                      "via rename (crash-before/after-rename window)",
    "wal.append": "coordination WAL about to frame+write an entry batch "
                  "(failure = write not acknowledged)",
    "wal.fsync": "coordination WAL about to fsync appended entries",
    "wal.snapshot": "coordination snapshot about to be written "
                    "(pre-atomic-rename crash window)",
    "device.score_ell": "ELL scoring dispatch seam (ops/ell.py "
                        "score_ell_batch) — the device nemesis' primary "
                        "injection point",
    "device.score_segments": "segmented scoring dispatch seam "
                             "(ops/ell.py score_segments_batch; hot "
                             "pass, cold walk, and parity oracle)",
    "device.score_coo": "COO scoring dispatch seam "
                        "(ops/scoring.py score_coo_batch)",
    "device.dense": "dense-plane dispatch seam (ops/dense.py "
                    "dense_scores / packed_dense_topk)",
    "device.upload": "tiering upload ring about to move one cold "
                     "segment host->HBM (engine/tiering.py)",
    "ensemble.vote": "ensemble member handling a RequestVote RPC",
    "ensemble.replicate_append.*": "ensemble leader about to send "
                                   "AppendEntries/InstallSnapshot to one "
                                   "peer (suffix: peer node id)",
}


class FaultInjected(RuntimeError):
    pass


@dataclass
class _Rule:
    action: str            # "raise" | "delay" | "callable"
    probability: float = 1.0
    delay_s: float = 0.0
    remaining: int | None = None   # fire at most N times; None = unlimited
    fn: object = None


class FaultInjector:
    def __init__(self, seed: int | None = None) -> None:
        self._rules: dict[str, _Rule] = {}
        self._lock = threading.Lock()
        self._rng = random.Random(seed)
        self.fired: dict[str, int] = {}

    def arm(self, point: str, action: str = "raise", probability: float = 1.0,
            delay_s: float = 0.0, times: int | None = None,
            fn=None) -> None:
        with self._lock:
            self._rules[point] = _Rule(action, probability, delay_s, times, fn)

    def disarm(self, point: str | None = None) -> None:
        with self._lock:
            if point is None:
                self._rules.clear()
            else:
                self._rules.pop(point, None)

    def _match(self, point: str) -> tuple[str, _Rule] | None:
        """Exact rule first, then any armed ``prefix*`` wildcard."""
        rule = self._rules.get(point)
        if rule is not None:
            return point, rule
        for key, r in self._rules.items():
            if key.endswith("*") and point.startswith(key[:-1]):
                return key, r
        return None

    def check(self, point: str) -> None:
        with self._lock:
            hit = self._match(point)
            if hit is None:
                return
            key, rule = hit
            if rule.remaining is not None:
                if rule.remaining <= 0:
                    return
            if self._rng.random() > rule.probability:
                return
            if rule.remaining is not None:
                rule.remaining -= 1
            # fires are counted under the RULE's name so wildcard chaos
            # configs can assert totals without enumerating instances
            self.fired[key] = self.fired.get(key, 0) + 1
            action, delay_s, fn = rule.action, rule.delay_s, rule.fn
        # every fault fire is visible in traces BY CONSTRUCTION: the one
        # emission here covers all fault_point()/check() sites (enforced
        # by the graftcheck registry-drift pass), so a chaos run's trace
        # shows exactly where the injected failure entered the request
        span_event("fault_injected", point=point, rule=key,
                   action=action)
        if action == "delay":
            time.sleep(delay_s)
        elif action == "callable" and fn is not None:
            fn()
        elif action == "raise":
            raise FaultInjected(f"fault injected at {point!r}")


# Process-wide injector used by library fault points; tests arm/disarm it.
global_injector = FaultInjector()


def fault_point(name: str) -> None:
    """Call at a named site; no-op unless a test armed this point."""
    global_injector.check(name)

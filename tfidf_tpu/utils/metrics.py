"""Counters, gauges, and log-bucketed latency histograms.

The reference exposes exactly one numeric metric — index size in bytes,
``GET /worker/index-size`` (``Worker.java:147-172``) — consumed by the upload
balancer (``Leader.java:170-185``). We keep that metric (as shard ``nnz`` and
byte size) and add the counters the reference never had (§5.5 of SURVEY.md):
docs indexed, queries served, collective timings, per-phase latencies.

``observe()`` feeds BOTH a cheap (count, sum, min, max) summary and a
fixed-boundary log-bucketed histogram, so :meth:`Metrics.quantile` and
the ``_p50_ms``/``_p95_ms``/``_p99_ms`` snapshot keys report LIVE tail
latency — the number the overload/admission story is about — instead of
means. Bucket boundaries are global and geometric (``_BUCKET_RATIO``
apart, 0.1 ms … ~120 s), so a quantile estimate is within one bucket
ratio of the true value by construction; estimates additionally clamp
to the observed [min, max] (a single-sample quantile is exact).

Counters and gauges are DISTINCT namespaces, enforced loudly: a name
registered as one kind raises if emitted as the other (the old code let
``snapshot()`` silently overwrite a counter with a same-named gauge and
``get()`` documented "counters win" — both hid the bug instead of
failing it). The Prometheus exposition keeps them distinct too:
counters render as ``tfidf_<name>_total``, gauges as ``tfidf_<name>``,
histograms as ``tfidf_<name>_seconds{_bucket,_sum,_count}``.
"""

from __future__ import annotations

import bisect
import math
import re
import threading
from collections import defaultdict
from typing import Any

# geometric histogram boundaries (seconds): 0.1 ms .. ~119 s, ratio 1.2
# per bucket. A quantile read off these buckets is within one ratio of
# the true value; README "Observability" documents the contract. Bounds
# are rounded to 4 significant digits so Prometheus ``le`` labels stay
# short and stable (the <0.05% rounding is noise next to the 20% ratio).
_BUCKET_RATIO = 1.2
_BUCKET_LO_S = 1e-4
_N_BUCKETS = 78   # _BUCKET_LO_S * 1.2**77 ≈ 125 s; beyond -> +Inf bucket
BUCKET_BOUNDS_S: tuple[float, ...] = tuple(
    float(f"{_BUCKET_LO_S * _BUCKET_RATIO ** i:.4g}")
    for i in range(_N_BUCKETS))


def bucket_quantile(counts: list[int], n: int, q: float,
                    mn: float | None = None,
                    mx: float | None = None) -> float | None:
    """Quantile estimate in SECONDS from raw histogram bucket counts
    (``len == len(BUCKET_BOUNDS_S) + 1``; last is +Inf): geometric
    interpolation inside the covering bucket. The ONE implementation
    shared by the cumulative-histogram quantiles below and the SLO
    autopilot's windowed deltas (cluster/autopilot.py) — a change to
    the bucket geometry or the interpolation cannot diverge between
    them. ``mn``/``mx`` clamp to observed extremes when the caller has
    them (the cumulative path); a window delta has none, so the +Inf
    bucket falls back to the last finite bound."""
    if n <= 0:
        return None
    target = min(max(1, math.ceil(q * n)), n)
    cum = 0
    idx = len(counts) - 1
    for i, c in enumerate(counts):
        cum += c
        if cum >= target:
            idx = i
            cum -= c   # cumulative BEFORE this bucket
            break
    if idx >= len(BUCKET_BOUNDS_S):       # +Inf bucket
        return mx if mx is not None else BUCKET_BOUNDS_S[-1]
    hi = BUCKET_BOUNDS_S[idx]
    lo = (BUCKET_BOUNDS_S[idx - 1] if idx > 0
          else hi / _BUCKET_RATIO)
    frac = (target - cum) / counts[idx]
    est = lo * (hi / lo) ** frac
    if mn is not None and mx is not None:
        est = min(max(est, mn), mx)
    return est


class MetricKindError(ValueError):
    """A metric name was emitted as both a counter and a gauge — the
    silent-shadowing bug class this guard exists to fail loudly."""


class Metrics:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[str, float] = defaultdict(float)
        self._gauges: dict[str, float] = {}
        # per-name summary [count, sum, min, max] + histogram bucket
        # counts (len == len(BUCKET_BOUNDS_S) + 1; last is +Inf)
        self._timings: dict[str, list[float]] = defaultdict(
            lambda: [0, 0.0, float("inf"), 0.0])
        self._hist: dict[str, list[int]] = defaultdict(
            lambda: [0] * (len(BUCKET_BOUNDS_S) + 1))

    def inc(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            if name in self._gauges:
                raise MetricKindError(
                    f"metric {name!r} is a gauge; inc() would shadow it")
            self._counters[name] += value

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            if name in self._counters:
                raise MetricKindError(
                    f"metric {name!r} is a counter; set_gauge() would "
                    f"shadow it")
            self._gauges[name] = value

    def observe(self, name: str, seconds: float) -> None:
        with self._lock:
            t = self._timings[name]
            t[0] += 1
            t[1] += seconds
            t[2] = min(t[2], seconds)
            t[3] = max(t[3], seconds)
            self._hist[name][bisect.bisect_left(BUCKET_BOUNDS_S,
                                                seconds)] += 1

    def get(self, name: str, default: float = 0.0) -> float:
        """Read one counter/gauge (the namespaces are disjoint — see
        the emit-side guards) — the resilience paths and tests branch
        on live values without paying for a full snapshot."""
        with self._lock:
            if name in self._counters:
                return self._counters[name]
            return self._gauges.get(name, default)

    def _quantile_locked(self, name: str, q: float) -> float | None:
        """Histogram quantile estimate in SECONDS; caller holds the
        lock. Geometric interpolation inside the covering bucket,
        clamped to the observed [min, max] (single-sample exactness;
        q=0/q=1 return the true extremes)."""
        t = self._timings.get(name)
        if t is None or not t[0]:
            return None
        n, _total, mn, mx = t
        if q <= 0.0:
            return mn
        if q >= 1.0:
            return mx
        return bucket_quantile(self._hist[name], n, q, mn=mn, mx=mx)

    def hist_snapshot(self, name: str) -> tuple[list[int], int] | None:
        """Copy of one histogram's raw bucket counts plus its total
        observation count, or None when nothing was observed. The SLO
        autopilot (cluster/autopilot.py) diffs two snapshots to get a
        WINDOWED distribution — the cumulative histogram alone would
        let hours-old samples outvote the last control interval."""
        with self._lock:
            if name not in self._timings or not self._timings[name][0]:
                return None
            return list(self._hist[name]), self._timings[name][0]

    def quantile(self, name: str, q: float) -> float | None:
        """Live latency quantile in seconds (e.g. ``quantile("scatter_rpc",
        0.99)``), or None when nothing was observed. Within one bucket
        ratio (``_BUCKET_RATIO``) of the true value by construction."""
        with self._lock:
            return self._quantile_locked(name, q)

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            out: dict[str, Any] = dict(self._counters)
            out.update(self._gauges)
            for name, (n, total, mn, mx) in self._timings.items():
                if n:
                    out[f"{name}_count"] = n
                    out[f"{name}_mean_ms"] = round(total / n * 1e3, 3)
                    out[f"{name}_min_ms"] = round(mn * 1e3, 3)
                    out[f"{name}_max_ms"] = round(mx * 1e3, 3)
                    # running sum: lets a scraper compute the mean over a
                    # WINDOW from two snapshots (delta sum / delta count)
                    out[f"{name}_sum_ms"] = round(total * 1e3, 3)
                    for label, q in (("p50", 0.5), ("p95", 0.95),
                                     ("p99", 0.99)):
                        v = self._quantile_locked(name, q)
                        out[f"{name}_{label}_ms"] = round(v * 1e3, 3)
            return out

    def render_prometheus(self,
                          extra_gauges: dict[str, float] | None = None
                          ) -> str:
        """Prometheus text exposition (format 0.0.4) of everything this
        registry holds: counters as ``tfidf_<name>_total``, gauges as
        ``tfidf_<name>`` (``extra_gauges`` lets the handler add derived
        values, e.g. breaker states), histograms as
        ``tfidf_<name>_seconds`` with cumulative ``_bucket`` series,
        ``_sum``, and ``_count``. Names are sanitized to the metric
        grammar; the two counter/gauge namespaces stay distinct in the
        output by construction (different rendered names)."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = {k: (list(v), list(self._timings[k]))
                     for k, v in self._hist.items()
                     if self._timings[k][0]}
        lines: list[str] = []
        for name, val in sorted(counters.items()):
            m = f"tfidf_{_sanitize(name)}_total"
            lines.append(f"# TYPE {m} counter")
            lines.append(f"{m} {_fmt(val)}")
        all_gauges = dict(gauges)
        all_gauges.update(extra_gauges or {})
        for name, val in sorted(all_gauges.items()):
            m = f"tfidf_{_sanitize(name)}"
            lines.append(f"# TYPE {m} gauge")
            lines.append(f"{m} {_fmt(val)}")
        for name, (counts, (n, total, _mn, _mx)) in sorted(
                hists.items()):
            m = f"tfidf_{_sanitize(name)}_seconds"
            lines.append(f"# TYPE {m} histogram")
            cum = 0
            for bound, c in zip(BUCKET_BOUNDS_S, counts):
                cum += c
                lines.append(
                    f'{m}_bucket{{le="{_fmt(bound)}"}} {cum}')
            lines.append(f'{m}_bucket{{le="+Inf"}} {n}')
            lines.append(f"{m}_sum {_fmt(total)}")
            lines.append(f"{m}_count {n}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._timings.clear()
            self._hist.clear()


_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def _sanitize(name: str) -> str:
    s = _NAME_BAD.sub("_", name)
    if s and s[0].isdigit():
        s = "_" + s
    return s


def _fmt(v: float) -> str:
    """Prometheus value formatting: integral floats without the
    trailing ``.0`` noise, everything else as repr (full precision)."""
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


global_metrics = Metrics()

"""Counters and gauges.

The reference exposes exactly one numeric metric — index size in bytes,
``GET /worker/index-size`` (``Worker.java:147-172``) — consumed by the upload
balancer (``Leader.java:170-185``). We keep that metric (as shard ``nnz`` and
byte size) and add the counters the reference never had (§5.5 of SURVEY.md):
docs indexed, queries served, collective timings, per-phase latencies.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Any


class Metrics:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[str, float] = defaultdict(float)
        self._gauges: dict[str, float] = {}
        # histogram-lite: (count, sum, min, max) per key
        self._timings: dict[str, list[float]] = defaultdict(
            lambda: [0, 0.0, float("inf"), 0.0])

    def inc(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += value

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def observe(self, name: str, seconds: float) -> None:
        with self._lock:
            t = self._timings[name]
            t[0] += 1
            t[1] += seconds
            t[2] = min(t[2], seconds)
            t[3] = max(t[3], seconds)

    def get(self, name: str, default: float = 0.0) -> float:
        """Read one counter/gauge (counters win on a name collision) —
        the resilience paths and tests branch on live values without
        paying for a full snapshot."""
        with self._lock:
            if name in self._counters:
                return self._counters[name]
            return self._gauges.get(name, default)

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            out: dict[str, Any] = dict(self._counters)
            out.update(self._gauges)
            for name, (n, total, mn, mx) in self._timings.items():
                if n:
                    out[f"{name}_count"] = n
                    out[f"{name}_mean_ms"] = round(total / n * 1e3, 3)
                    out[f"{name}_min_ms"] = round(mn * 1e3, 3)
                    out[f"{name}_max_ms"] = round(mx * 1e3, 3)
                    # running sum: lets a scraper compute the mean over a
                    # WINDOW from two snapshots (delta sum / delta count)
                    out[f"{name}_sum_ms"] = round(total * 1e3, 3)
            return out

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._timings.clear()


global_metrics = Metrics()

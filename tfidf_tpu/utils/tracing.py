"""Tracing / profiling hooks.

The reference has none (SURVEY.md §5.1) — its only visibility is log lines
around each request. Here every pipeline phase (analyze / vectorize / score /
top-k / collective) runs inside ``trace_phase``, which (a) records wall time
into the global metrics, and (b) opens a ``jax.profiler.TraceAnnotation`` so
phases show up named in TensorBoard/Perfetto traces captured with
``jax.profiler.start_trace``.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator

from tfidf_tpu.utils.metrics import global_metrics

try:  # jax is always present in this image, but keep host-only tools usable
    import jax.profiler as _jprof
except Exception:  # pragma: no cover
    _jprof = None


@contextlib.contextmanager
def trace_phase(name: str) -> Iterator[None]:
    t0 = time.perf_counter()
    ann = (_jprof.TraceAnnotation(name) if _jprof is not None
           else contextlib.nullcontext())
    with ann:
        try:
            yield
        finally:
            global_metrics.observe(f"phase_{name}", time.perf_counter() - t0)


def phase_timings() -> dict[str, float]:
    """Snapshot of per-phase timing stats (phase_* keys only)."""
    return {k: v for k, v in global_metrics.snapshot().items()
            if k.startswith("phase_")}


@contextlib.contextmanager
def profile_to(logdir: str) -> Iterator[None]:
    """Capture a full XLA/TPU profiler trace into ``logdir``."""
    if _jprof is None:  # pragma: no cover
        yield
        return
    _jprof.start_trace(logdir)
    try:
        yield
    finally:
        _jprof.stop_trace()

"""Distributed tracing: Dapper-style spans + the phase/profiler hooks.

The reference has none (SURVEY.md §5.1) — its only visibility is log
lines around each request. PR 1–8 grew a cluster that survives worker
SIGKILL, partitions, fencing step-downs, hedged reads, and overload
shedding, but nothing reconstructed *which* batch a slow query coalesced
into, which workers it scattered to, or which retries/hedges/failovers
fired along the way. This module adds that reconstruction:

- a trace context (trace id, span id, parent id) minted at admission in
  :mod:`tfidf_tpu.cluster.node` and carried as ``X-Trace-Id`` /
  ``X-Span-Id`` headers across every leader→worker RPC (the same shared
  HTTP seams the nemesis shim instruments);
- spans *linked* (not parented) through the coalescer: one batch span
  references the N request spans it absorbed, and each request span
  links back to its batch, so a trace walk crosses the coalescing
  boundary in either direction;
- span **events** from the resilience layer (retry attempts, breaker
  trips, hedge dispatches/wins, failover slices, 429 sheds, fence
  rejections, fault-point fires) and the worker's pipeline stages —
  with the existing :func:`trace_phase` phases (analyze / vectorize /
  score / topk) folding into the active span, so engine-level timings
  land inside the request timeline;
- a bounded, lock-free in-process ring buffer of finished spans
  (one stable ``collections.deque``, trim-bounded — appends and
  popleft trims are GIL-atomic), exported
  by ``GET /api/trace`` (by trace id or recent-N), a
  Chrome-trace/Perfetto JSON exporter (:func:`to_chrome_trace`), a
  threshold-gated slow-query log keyed by trace id, and the CLI
  ``trace`` subcommand.

Sampling: the decision is made once, when a ROOT span is minted
(``sample_rate``); children and remote continuations inherit it. An
unsampled span still carries real ids (so the LOCAL node's log lines
stay joinable) but skips event recording, is never written to the
ring, and never propagates headers — with ``trace_sample_rate=0`` the
per-request cost is one object allocation and two contextvar
operations.

``trace_phase`` keeps its original contract: it records wall time into
the global metrics and opens a ``jax.profiler.TraceAnnotation`` so
phases show up named in TensorBoard/Perfetto captures — and now ALSO
stamps a ``phase.<name>`` event on the active span.
"""

from __future__ import annotations

import contextlib
import contextvars
import random
import re
import time
from collections import deque
from typing import Iterator, NamedTuple

from tfidf_tpu.utils.metrics import global_metrics

try:  # jax is always present in this image, but keep host-only tools usable
    import jax.profiler as _jprof
except Exception:  # pragma: no cover
    _jprof = None

# the propagation headers (the trace analog of the fencing layer's
# X-Leader-Epoch): injected by the shared HTTP client helpers in
# cluster/node.py, read back by the worker-side handlers
TRACE_HEADER = "X-Trace-Id"
SPAN_HEADER = "X-Span-Id"


def _epoch_anchor() -> float:
    """Wall-clock anchor for span timestamps: one ``time.time()`` read
    at import, after which every span start is ``anchor + monotonic()``
    — timestamps stay human-meaningful (Chrome trace wants epoch
    microseconds) while all span *arithmetic* rides the monotonic
    clock, immune to NTP steps mid-trace (graftcheck wallclock pass:
    this single read is the reviewed exception)."""
    return time.time() - time.monotonic()


_EPOCH0 = _epoch_anchor()


def epoch_now() -> float:
    """Epoch seconds derived from the reviewed wall-clock anchor plus
    the monotonic clock — the timestamp helper for records that must
    be human-meaningful (span starts, autopilot decisions) without
    adding new raw ``time.time()`` reads (graftcheck wallclock pass)."""
    return _EPOCH0 + time.monotonic()

# per-process id entropy: span ids must not collide across the nodes of
# an in-process test cluster, so the generator is seeded from urandom.
# No lock: getrandbits/random are single C-level calls, GIL-atomic in
# CPython — the record path stays lock-free by design.
_rng = random.Random()


def _new_id(bits: int) -> str:
    return f"{_rng.getrandbits(bits):0{bits // 4}x}"


# the id grammar accepted from UNTRUSTED propagation headers (ours are
# 16-hex trace / 8-hex span ids; W3C-style 32-hex accepted too)
_ID_RE = re.compile(r"[0-9a-f]{8,64}")


class SpanContext(NamedTuple):
    """The wire-propagatable part of a span: what ``X-Trace-Id`` /
    ``X-Span-Id`` carry, and what links reference."""
    trace_id: str
    span_id: str
    sampled: bool = True


class Span:
    """One timed operation. Mutation is append-only under the GIL
    (list.append / attribute set), so events from pipeline/pool threads
    need no locking; the span is exported only after :meth:`finish`."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "sampled",
                 "start_s", "end_s", "attrs", "events", "links")

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: str | None, sampled: bool,
                 attrs: dict | None = None) -> None:
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.sampled = sampled
        self.start_s = _EPOCH0 + time.monotonic()
        self.end_s: float | None = None
        self.attrs: dict = dict(attrs) if attrs else {}
        # bounded, oldest-dropped: a retry/hedge storm must not grow
        # the ring's memory unboundedly, and the cap must keep the
        # NEWEST events — the late decisive ones (scatter.health
        # verdict, hedge_win) are exactly what chaos suites assert on
        self.events: deque[tuple[float, str, dict]] = deque(
            maxlen=self._MAX_EVENTS)
        self.links: list[tuple[str, str]] = []   # (trace_id, span_id)

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id, self.sampled)

    # per-span event bound (deque maxlen: appends past it drop the
    # OLDEST entry, GIL-atomically)
    _MAX_EVENTS = 512

    def event(self, name: str, **attrs) -> None:
        """Timestamped annotation on this span (retry, breaker trip,
        hedge win, fault fire, pipeline stage, …). No-op when the
        trace is unsampled; bounded per span (newest kept)."""
        if self.sampled:
            self.events.append((_EPOCH0 + time.monotonic(), name, attrs))

    def set_attr(self, key: str, value) -> None:
        if self.sampled:
            self.attrs[key] = value

    def add_link(self, ctx: SpanContext) -> None:
        """Reference a span in ANOTHER trace (the coalescer boundary:
        batch spans link the request spans they absorbed, and vice
        versa). Links are how ``get_trace`` walks across traces."""
        if self.sampled:
            self.links.append((ctx.trace_id, ctx.span_id))

    def to_dict(self) -> dict:
        d = {"trace_id": self.trace_id, "span_id": self.span_id,
             "parent_id": self.parent_id, "name": self.name,
             "start_s": round(self.start_s, 6),
             "duration_ms": round(((self.end_s or self.start_s)
                                   - self.start_s) * 1e3, 3),
             "attrs": dict(self.attrs),
             "events": [{"t_s": round(t, 6), "name": n,
                         "attrs": dict(a)}
                        for t, n, a in list(self.events)],
             "links": [{"trace_id": t, "span_id": s}
                       for t, s in list(self.links)]}
        return d


class Tracer:
    """Process-wide span factory + bounded ring buffer of finished
    spans. The ring is ONE stable ``deque`` bounded by popleft trims
    (never a maxlen rebind — see ``__init__``): appends, trims, and
    snapshot reads are GIL-atomic, so the serving hot path never takes
    a lock to record a span."""

    def __init__(self, max_spans: int = 4096,
                 sample_rate: float = 1.0) -> None:
        # ONE deque for the tracer's whole lifetime: the bound is
        # enforced by trimming, never by rebinding — a rebind would
        # race concurrent finish() appends into a discarded object
        # (the lock-free record path depends on the reference being
        # stable)
        self._ring: deque[Span] = deque()
        self.max_spans = max(16, max_spans)
        self.sample_rate = sample_rate
        self._current: contextvars.ContextVar[Span | None] = \
            contextvars.ContextVar("tfidf_span", default=None)

    def configure(self, max_spans: int | None = None,
                  sample_rate: float | None = None) -> None:
        """Apply Config knobs (idempotent; called by SearchNode). A
        max_spans change re-bounds the ring in place, keeping the
        newest."""
        if sample_rate is not None:
            self.sample_rate = sample_rate
        if max_spans is not None:
            self.max_spans = max(16, max_spans)
            self._trim()

    def _trim(self) -> None:
        # append+popleft are each GIL-atomic; concurrent trimmers can
        # only over-pop by a handful of spans (harmless), never corrupt
        while len(self._ring) > self.max_spans:
            try:
                self._ring.popleft()
            except IndexError:   # raced another trimmer on empty
                break

    # ---- span lifecycle ----

    def current(self) -> Span | None:
        return self._current.get()

    def start(self, name: str,
              parent: "Span | SpanContext | None" = None,
              attrs: dict | None = None, *,
              links: "list[SpanContext] | None" = None,
              sampled: bool | None = None) -> Span:
        """Create (but do not activate) a span. With no parent this
        mints a new root trace and draws the sampling decision; with a
        parent (local span or remote context) the trace id and sampled
        flag are inherited. ``sampled`` overrides the root draw — a
        root that exists ONLY because of already-sampled spans (the
        coalescer's batch span, which links sampled requests) must
        inherit their verdict, not re-roll it: an independent draw
        would drop a sampled request's entire scatter sub-trace with
        probability (1 - sample_rate)."""
        if parent is None:
            trace_id = _new_id(64)
            if sampled is None:
                sampled = (self.sample_rate >= 1.0
                           or _rng.random() < self.sample_rate)
            parent_id = None
        else:
            ctx = parent.context if isinstance(parent, Span) else parent
            trace_id, parent_id, sampled = (ctx.trace_id, ctx.span_id,
                                            ctx.sampled)
        span = Span(name, trace_id, _new_id(32), parent_id, sampled,
                    attrs)
        if links:
            for ctx in links:
                span.add_link(ctx)
        return span

    def finish(self, span: Span) -> None:
        span.end_s = _EPOCH0 + time.monotonic()
        if span.sampled:
            self._ring.append(span)
            self._trim()

    @contextlib.contextmanager
    def span(self, name: str,
             parent: "Span | SpanContext | None" = None,
             attrs: dict | None = None, *,
             links: "list[SpanContext] | None" = None,
             sampled: bool | None = None) -> Iterator[Span]:
        """Start + ACTIVATE a span for the ``with`` body: it becomes
        :meth:`current` on this thread (contextvar token-reset on
        exit), gets an ``error`` attr if the body raises, and is
        finished into the ring either way."""
        sp = self.start(name, parent=parent, attrs=attrs, links=links,
                        sampled=sampled)
        token = self._current.set(sp)
        try:
            yield sp
        except BaseException as e:
            sp.set_attr("error", repr(e)[:200])
            raise
        finally:
            self._current.reset(token)
            self.finish(sp)

    @contextlib.contextmanager
    def activate(self, span: Span | None) -> Iterator[None]:
        """Make an EXISTING span current for the ``with`` body (used by
        pipeline stage threads that execute work submitted under a
        span). Does not finish it. ``None`` is a no-op."""
        if span is None:
            yield
            return
        token = self._current.set(span)
        try:
            yield
        finally:
            self._current.reset(token)

    # ---- export ----

    def recent(self, n: int = 100) -> list[dict]:
        """The newest ``n`` finished spans, newest first."""
        if n <= 0:
            return []
        snap = list(self._ring)
        return [s.to_dict() for s in snap[-n:]][::-1]

    def get_trace(self, trace_id: str,
                  follow_links: bool = True) -> list[dict]:
        """Every finished span of ``trace_id``, start-ordered — plus,
        with ``follow_links``, the spans of every trace reachable over
        one link hop (the coalescer boundary: a request trace pulls in
        its batch trace's scatter/worker/failover spans, and a batch
        trace pulls in its absorbed requests)."""
        snap = list(self._ring)
        want = {trace_id}
        if follow_links:
            for s in snap:
                if s.trace_id == trace_id:
                    want.update(t for t, _sid in s.links)
                elif any(t == trace_id for t, _sid in s.links):
                    want.add(s.trace_id)
        out = [s for s in snap if s.trace_id in want]
        out.sort(key=lambda s: s.start_s)
        return [s.to_dict() for s in out]

    def clear(self) -> None:
        self._ring.clear()


global_tracer = Tracer()


# ---- module-level conveniences (the hot-path API) ----

def current_span() -> Span | None:
    return global_tracer.current()


def current_trace_id() -> str | None:
    """The active trace id (for log-record correlation), or None."""
    s = global_tracer.current()
    return s.trace_id if s is not None else None


def span_event(name: str, **attrs) -> None:
    """Annotate the active span; no-op with no span active (so library
    code — resilience retries, breaker trips, fault fires — can emit
    unconditionally without caring whether a request is traced)."""
    s = global_tracer.current()
    if s is not None:
        s.event(name, **attrs)


def propagation_headers() -> dict[str, str]:
    """``X-Trace-Id``/``X-Span-Id`` for the active span (empty when no
    span is active). The shared HTTP helpers in cluster/node.py merge
    this into every outbound request, so the trace context crosses
    every leader→worker RPC by construction."""
    s = global_tracer.current()
    if s is None or not s.sampled:
        # an unsampled trace never propagates: downstream spans would
        # be recorded against a root nobody kept (remote continuations
        # are always treated as sampled)
        return {}
    return {TRACE_HEADER: s.trace_id, SPAN_HEADER: s.span_id}


def remote_context(trace_id: str | None, span_id: str | None,
                   trusted: bool = True) -> SpanContext | None:
    """Rebuild the propagated context from incoming headers (None when
    the request is untraced).

    ``trusted`` (the worker plane's leader→worker continuation): the
    sampling decision was made where the root was minted, and an
    unsampled trace never propagates — so the context is sampled
    whenever this node has tracing enabled at all.

    Untrusted (the public ``/leader/*`` front door): the caller keeps
    its trace id — correlation still works end to end — but recording
    is subject to THIS node's own sampling draw, exactly like a
    locally-minted root. A client attaching ``X-Trace-Id`` headers
    must not buy 100% recording under a partial ``trace_sample_rate``
    (it would control ring retention and recording cost)."""
    if not trace_id:
        return None
    # ids must be well-formed hex on BOTH paths (ours are 16/8 chars;
    # W3C-style up to 32 accepted) — the worker endpoints share the
    # public listener, so even the "trusted" continuation can carry a
    # hostile header: arbitrary bytes must never be stored in the
    # ring, stamped into key=value log lines (field-injection into
    # the machine-parseable stream), or echoed through response
    # headers. Our own leader always sends valid hex, so the check
    # costs one regex per RPC. Malformed ids fall back to a
    # freshly-minted root.
    if _ID_RE.fullmatch(trace_id) is None or (
            span_id and _ID_RE.fullmatch(span_id) is None):
        return None
    rate = global_tracer.sample_rate
    if trusted:
        sampled = rate > 0
    else:
        sampled = rate >= 1.0 or _rng.random() < rate
    return SpanContext(trace_id, span_id or "", sampled)


# ---- rendering ----

def to_chrome_trace(spans: list[dict]) -> dict:
    """Chrome-trace/Perfetto JSON (``chrome://tracing`` / ui.perfetto.dev
    both load it): one complete ("X") event per span on a per-trace
    track, instant ("i") events for span events."""
    events = []
    tids = {}
    for s in spans:
        tid = tids.setdefault(s["trace_id"], len(tids) + 1)
        events.append({
            "ph": "X", "name": s["name"], "pid": 1, "tid": tid,
            "ts": round(s["start_s"] * 1e6, 1),
            "dur": round(s["duration_ms"] * 1e3, 1),
            "args": {**s["attrs"], "span_id": s["span_id"],
                     "trace_id": s["trace_id"]}})
        for ev in s["events"]:
            events.append({
                "ph": "i", "name": ev["name"], "pid": 1, "tid": tid,
                "ts": round(ev["t_s"] * 1e6, 1), "s": "t",
                "args": dict(ev["attrs"])})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def render_trace_tree(spans: list[dict]) -> str:
    """Human-readable timeline: spans as an indented tree (parent →
    children by span id; link-only spans grouped under their linking
    root), one line per span with offset/duration and its events. The
    CLI ``trace`` subcommand and ``make trace-demo`` both print this."""
    if not spans:
        return "(no spans)"
    by_id = {s["span_id"]: s for s in spans}
    children: dict[str | None, list[dict]] = {}
    for s in spans:
        pid = s["parent_id"] if s["parent_id"] in by_id else None
        children.setdefault(pid, []).append(s)
    for v in children.values():
        v.sort(key=lambda s: s["start_s"])
    t0 = min(s["start_s"] for s in spans)
    out: list[str] = []

    def walk(s: dict, depth: int) -> None:
        off = (s["start_s"] - t0) * 1e3
        attrs = " ".join(f"{k}={v}" for k, v in sorted(
            s["attrs"].items()))
        out.append(f"{'  ' * depth}{off:8.1f}ms "
                   f"+{s['duration_ms']:.1f}ms  {s['name']}"
                   f"  [{s['trace_id'][:8]}]"
                   + (f"  {attrs}" if attrs else ""))
        for ev in s["events"]:
            eoff = (ev["t_s"] - t0) * 1e3
            ea = " ".join(f"{k}={v}" for k, v in sorted(
                ev["attrs"].items()))
            out.append(f"{'  ' * depth}  {eoff:8.1f}ms   "
                       f"· {ev['name']}" + (f"  {ea}" if ea else ""))
        for c in children.get(s["span_id"], ()):
            walk(c, depth + 1)

    for root in children.get(None, ()):
        walk(root, 0)
    return "\n".join(out)


# ---- phase timing hooks (original API, now span-aware) ----

@contextlib.contextmanager
def trace_phase(name: str) -> Iterator[None]:
    t0 = time.perf_counter()
    ann = (_jprof.TraceAnnotation(name) if _jprof is not None
           else contextlib.nullcontext())
    with ann:
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            global_metrics.observe(f"phase_{name}", dt)
            # fold the engine phase into the request timeline: lands on
            # whatever span is active (the worker's process-batch span,
            # or a pipeline stage's activated submit-time span)
            span_event(f"phase.{name}", ms=round(dt * 1e3, 3))


def phase_timings() -> dict[str, float]:
    """Snapshot of per-phase timing stats (phase_* keys only)."""
    return {k: v for k, v in global_metrics.snapshot().items()
            if k.startswith("phase_")}

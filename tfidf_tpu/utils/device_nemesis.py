"""Scriptable device nemesis: fault injection at the JAX dispatch seam.

The cluster nemesis (``cluster/nemesis.py``) breaks the network, the
storage nemesis (``utils/storage.py`` fault points) breaks the disk —
this module breaks the *compute plane*: the jit-call seams in
``ops/ell.py`` / ``ops/scoring.py`` / ``ops/dense.py`` and the tiering
upload ring (``engine/tiering.py``) consult it right before dispatching
device work, so a chaos run can inject exactly the failure modes a real
accelerator produces:

- ``oom``       — HBM ``RESOURCE_EXHAUSTED`` on allocation
                  (:class:`DeviceOOMError`); with ``min_batch`` set the
                  rule fires only for query batches at or above that
                  size, which is how the OOM backoff ladder is tested
                  (B fails, B/2 succeeds).
- ``compile``   — XLA compilation failure (:class:`DeviceCompileError`).
- ``transient`` — a transient ``XlaRuntimeError``-shaped runtime fault
                  (:class:`DeviceTransientError`).
- ``poison``    — NaN-poisoned output buffers: the seam's wrapper gets
                  a ``"poison"`` verdict back and corrupts the rows of
                  queries with at least ``min_uniq`` distinct terms —
                  modelling a query whose *shape* deterministically
                  breaks the kernel, the case the leader's poison
                  quarantine exists for. No exception is raised at the
                  dispatch site; detection happens at the fetch seam
                  (``Searcher._assemble``), exactly where a real
                  miscompiled kernel's garbage would first be seen.
- ``delay``     — dispatch latency (sleeps ``delay_s``): the wedged /
                  slow device.
- ``sick``      — sticky sick-device mode: once fired, EVERY guarded
                  dispatch raises :class:`DeviceSickError` until
                  :meth:`DeviceNemesis.heal` — the device that needs a
                  restart, not a retry.

Design grammar follows ``cluster/nemesis.py``: immutable rules in a
copy-on-write tuple (writers replace the tuple under ``_lock``; the
read path is one attribute read plus an emptiness check, so an unarmed
nemesis costs nothing on the hot dispatch path), a process-global
singleton (:data:`global_device_nemesis`), and env arming via
``TFIDF_DEVICE_NEMESIS`` for subprocess chaos harnesses::

    TFIDF_DEVICE_NEMESIS="score_ell:oom:1.0:min_batch=64,*:delay:0.5:delay_s=0.02"

(comma-separated ``site:kind[:probability[:k=v;k=v]]`` entries; ``site``
is an exact seam name or a ``prefix*`` glob, ``*`` matches every seam).

Every guarded seam is also a registered ``device.*`` fault point
(:data:`tfidf_tpu.utils.faults.KNOWN_FAULT_POINTS`), so generic chaos
configs and the fault-registry drift check cover the compute plane like
every other plane, and each nemesis fire emits the same
``fault_injected`` trace event the plain injector does.
"""

from __future__ import annotations

import fnmatch
import itertools
import os
import threading
import time
from dataclasses import dataclass, field

from tfidf_tpu.utils.metrics import global_metrics
from tfidf_tpu.utils.tracing import span_event


class DeviceFault(RuntimeError):
    """Base for injected (and classified) compute-plane faults."""


class DeviceOOMError(DeviceFault):
    """Injected HBM allocation failure (RESOURCE_EXHAUSTED shape)."""


class DeviceCompileError(DeviceFault):
    """Injected XLA compilation failure."""


class DeviceTransientError(DeviceFault):
    """Injected transient device runtime error."""


class DeviceSickError(DeviceFault):
    """Sticky sick-device mode: every dispatch fails until heal()."""


class DevicePoisonedOutput(DeviceFault):
    """Non-finite device output detected at the fetch seam.

    Carries the query strings whose result rows were poisoned, so the
    worker can report per-query blame (``X-Poison-Fingerprints``) and
    the leader's quarantine never punishes innocent cohort queries that
    merely shared the batch."""

    def __init__(self, queries: tuple[str, ...] = ()) -> None:
        super().__init__(
            f"non-finite device output for {len(queries)} query row(s)")
        self.queries = tuple(queries)


_KINDS = ("oom", "compile", "transient", "poison", "delay", "sick")

_RAISES = {
    "oom": lambda site: DeviceOOMError(
        f"RESOURCE_EXHAUSTED: injected HBM OOM at device.{site}"),
    "compile": lambda site: DeviceCompileError(
        f"injected XLA compilation failure at device.{site}"),
    "transient": lambda site: DeviceTransientError(
        f"injected transient device error at device.{site}"),
    "sick": lambda site: DeviceSickError(
        f"device sick (injected at device.{site})"),
}


@dataclass(frozen=True)
class _Rule:
    rid: int
    site: str                 # exact seam name, "prefix*", or "*"
    kind: str                 # one of _KINDS
    probability: float = 1.0
    min_batch: int = 0        # fire only when batch cap >= this
    min_uniq: int = 0         # fire only when distinct terms >= this
    count: int | None = None  # fire at most N times; None = unlimited
    delay_s: float = 0.0
    fired: list = field(default_factory=lambda: [0], compare=False)


class DeviceNemesis:
    """Copy-on-write rule set consulted by the device dispatch seams."""

    def __init__(self, env: str | None = None) -> None:
        self._lock = threading.Lock()       # writers only
        self._rules: tuple[_Rule, ...] = ()
        self._sick = False
        self._rid = itertools.count(1)
        spec = (os.environ.get("TFIDF_DEVICE_NEMESIS", "")
                if env is None else env)
        if spec:
            self.script(spec)

    # ---- writer API (copy-on-write; the read path never locks) ----

    def add_rule(self, site: str, kind: str, *, probability: float = 1.0,
                 min_batch: int = 0, min_uniq: int = 0,
                 count: int | None = None, delay_s: float = 0.0) -> int:
        if kind not in _KINDS:
            raise ValueError(f"unknown device-nemesis kind {kind!r} "
                             f"(want one of {_KINDS})")
        with self._lock:
            rid = next(self._rid)
            rule = _Rule(rid, site, kind, probability, min_batch,
                         min_uniq, count, delay_s)
            self._rules = self._rules + (rule,)
            return rid

    def remove_rule(self, rid: int) -> bool:
        with self._lock:
            keep = tuple(r for r in self._rules if r.rid != rid)
            hit = len(keep) != len(self._rules)
            self._rules = keep
            return hit

    def clear(self) -> None:
        """Drop every rule AND lift sick mode (the chaos teardown)."""
        with self._lock:
            self._rules = ()
            self._sick = False

    def heal(self) -> None:
        """Lift sticky sick mode (rules stay armed)."""
        self._sick = False

    def script(self, spec: str) -> list[int]:
        """Arm from a ``TFIDF_DEVICE_NEMESIS``-format string; returns
        the new rule ids."""
        rids = []
        for entry in spec.split(","):
            entry = entry.strip()
            if not entry:
                continue
            parts = entry.split(":")
            if len(parts) < 2:
                raise ValueError(
                    f"bad device-nemesis entry {entry!r} "
                    f"(want site:kind[:probability[:k=v;k=v]])")
            site, kind = parts[0], parts[1]
            prob = float(parts[2]) if len(parts) > 2 and parts[2] else 1.0
            kw: dict = {}
            if len(parts) > 3 and parts[3]:
                for kv in parts[3].split(";"):
                    k, _, v = kv.partition("=")
                    k = k.strip()
                    if k == "delay_s":
                        kw[k] = float(v)
                    elif k in ("min_batch", "min_uniq", "count"):
                        kw[k] = int(v)
                    else:
                        raise ValueError(
                            f"unknown device-nemesis option {k!r}")
            rids.append(self.add_rule(site, kind, probability=prob, **kw))
        return rids

    # ---- read path ----

    @property
    def armed(self) -> bool:
        return bool(self._rules) or self._sick

    @property
    def sick(self) -> bool:
        return self._sick

    def snapshot(self) -> dict:
        rules = self._rules
        return {"sick": self._sick,
                "rules": [{"rid": r.rid, "site": r.site, "kind": r.kind,
                           "probability": r.probability,
                           "min_batch": r.min_batch,
                           "min_uniq": r.min_uniq, "count": r.count,
                           "delay_s": r.delay_s, "fired": r.fired[0]}
                          for r in rules]}

    def check(self, site: str, *, batch: int = 0,
              uniq: int = 0) -> "_Rule | None":
        """Consult the rules at one dispatch seam.

        Returns the fired poison rule when a poison rule fired (the
        caller corrupts the output rows its ``min_uniq`` selects via
        :func:`poison_rows_mask`), ``None`` when nothing fired; raises
        the typed fault for oom/compile/transient/sick; sleeps for
        delay rules. Sticky sick mode fails every seam until
        :meth:`heal`."""
        if self._sick:
            self._fired(site, "sick")
            raise _RAISES["sick"](site)
        rules = self._rules
        if not rules:
            return None
        import random
        for r in rules:
            if r.count is not None and r.fired[0] >= r.count:
                continue
            if not (r.site == "*" or r.site == site
                    or (r.site.endswith("*")
                        and fnmatch.fnmatch(site, r.site))):
                continue
            if batch < r.min_batch:
                continue
            # min_uniq gates non-poison rules on the (optional) batch
            # uniq hint; for poison rules it is a ROW filter instead —
            # poison_scores() corrupts only rows with >= min_uniq
            # distinct terms, so the rule must fire regardless of the
            # batch-level hint
            if r.kind != "poison" and r.min_uniq and uniq < r.min_uniq:
                continue
            if r.probability < 1.0 and random.random() > r.probability:
                continue
            r.fired[0] += 1
            self._fired(site, r.kind)
            if r.kind == "delay":
                time.sleep(r.delay_s)
                continue
            if r.kind == "poison":
                return r
            if r.kind == "sick":
                self._sick = True
            raise _RAISES[r.kind](site)
        return None

    def _fired(self, site: str, kind: str) -> None:
        global_metrics.inc("device_nemesis_fired")
        span_event("fault_injected", point=f"device.{site}",
                   rule=f"device_nemesis:{kind}", action=kind)


# Process-wide nemesis consulted by the dispatch seams; chaos harnesses
# arm it directly (same process) or via TFIDF_DEVICE_NEMESIS (worker
# subprocesses).
global_device_nemesis = DeviceNemesis()


def device_guard(site: str, *, batch: int = 0,
                 uniq: int = 0) -> "_Rule | None":
    """The one call every guarded dispatch seam makes: the registered
    ``device.<site>`` fault point (generic injector) plus the scripted
    nemesis. Unarmed cost: two dict/attribute lookups."""
    from tfidf_tpu.utils.faults import global_injector
    global_injector.check("device." + site)
    nem = global_device_nemesis
    if not nem.armed:
        return None
    return nem.check(site, batch=batch, uniq=uniq)


def poison_scores(scores, weights, min_uniq: int):
    """Corrupt a fired poison rule's target rows with NaN — entirely ON
    DEVICE (a ``jnp.where`` over the score matrix), so the injection
    itself never adds a host<->device transfer the device witness would
    have to explain. Rows with at least ``min_uniq`` nonzero term
    weights are poisoned (``min_uniq`` 0 poisons every row), modelling
    a query shape that deterministically breaks the kernel while its
    batch cohort scores fine."""
    import jax.numpy as jnp
    if min_uniq <= 0:
        return jnp.full_like(scores, jnp.nan)
    mask = (weights > 0).sum(axis=1) >= min_uniq       # [B] on device
    return jnp.where(mask[:, None], jnp.float32(jnp.nan), scores)

"""Single-dataclass configuration with environment-variable overrides.

The reference configures itself through Spring ``application.properties``
(``src/main/resources/application.properties:1-8`` — ``zookeeper.connection``,
``mydocument.path``, ``lucene.index.path``, ``server.port``) plus raw env vars
``POD_IP`` / ``SERVER_PORT`` read in ``OnElectionAction.java:35-36,64-68``.
Here the whole surface is one frozen dataclass; every field can be overridden
by a ``TFIDF_<UPPER_NAME>`` environment variable, so a Kubernetes Deployment
can configure nodes exactly the way the reference's manifest does
(``README.MD:80-90``).
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass
from typing import Any

_ENV_PREFIX = "TFIDF_"


@dataclass(frozen=True)
class Config:
    # --- paths (reference: application.properties:5-7) ---
    documents_path: str = "./data/documents"
    index_path: str = "./data/index"

    # --- node / control plane (reference: application.properties:2,8) ---
    # May be a comma-separated ensemble connect string
    # ("c0:2181,c1:2181,c2:2181") — clients fail over across members and
    # follow follower->leader redirects (cluster/coordination.py).
    coordinator_address: str = "127.0.0.1:2181"
    host: str = "127.0.0.1"
    port: int = 8085
    # Liveness: the reference's ZooKeeper session timeout doubles as the
    # failure detector (ZookeeperConfig.java:17, sessionTimeout=3000ms).
    session_timeout_s: float = 3.0
    heartbeat_interval_s: float = 0.5

    # --- scoring model ---
    model: str = "bm25"          # "bm25" | "tfidf" | "tfidf_cosine"
    bm25_k1: float = 1.2         # Lucene BM25Similarity defaults
    bm25_b: float = 0.75
    # Parity mode reproduces Lucene quirks bit-for-bit: SmallFloat 1-byte
    # norm quantization and per-shard (non-global) IDF (Worker.java:222-241).
    lucene_parity: bool = False
    # Result ordering: the reference sorts by document NAME, not score
    # (Leader.java:80-91, comparingByKey). "score" is the sane default.
    result_order: str = "score"  # "score" | "name"
    top_k: int = 10
    # Parity mode for the cluster data plane: return EVERY matching doc
    # per query (the reference's Integer.MAX_VALUE top-k, Worker.java:230)
    # instead of exact top-k. O(corpus) per query — off by default.
    unbounded_results: bool = False
    # Server-side micro-batching of concurrent /worker/process queries
    # into one device batch; the linger is the max extra latency a lone
    # query pays while waiting for company.
    micro_batch: bool = True
    batch_linger_ms: float = 2.0
    # Adaptive linger bounds (serving pipeline, PERF.md round 6): with
    # no batch in flight the coalescer lingers only *_linger_min_ms
    # (the device is idle — dispatch at once); as the dispatcher
    # pipeline saturates the linger stretches toward *_linger_max_ms
    # (the wait hides under in-flight work and buys batch fill). Set
    # either bound negative to disable adaptation and keep the fixed
    # *_linger_ms. Env overrides: TFIDF_BATCH_LINGER_MIN_MS etc.
    batch_linger_min_ms: float = 0.2
    batch_linger_max_ms: float = 4.0
    # Concurrent in-flight micro-batches (scorer threads). 2 hides one
    # batch's device->host result fetch under the next batch's compute —
    # material on high-RTT device links (remote-TPU tunnels).
    batch_pipeline: int = 2
    # Leader scatter fan-out thread pool. Each in-flight /leader/start
    # holds one pool thread per worker RPC; with C concurrent clients
    # and W workers the pool needs ~C*W threads or the scatter itself
    # becomes the concurrency cap (and the worker micro-batcher never
    # sees full batches). (With scatter_micro_batch on, only the
    # dispatcher threads use the pool: ~scatter_pipeline * W.)
    fanout_workers: int = 16
    # Leader-side scatter batching: concurrent /leader/start queries
    # coalesce into ONE /worker/process-batch RPC per worker (packed
    # binary response, cluster/wire.py) instead of one JSON RPC per
    # (query, worker). At high client concurrency the per-query HTTP +
    # JSON Python cost on the worker is the serving-path ceiling
    # (GIL-bound); batching collapses it to one RPC per batch.
    # Unbounded-results (parity) configs use the per-query path.
    scatter_micro_batch: bool = True
    scatter_batch: int = 128
    scatter_linger_ms: float = 2.0
    # Adaptive scatter linger (same rule as batch_linger_min/max_ms):
    # idle pipeline -> linger_min (ship the group now), saturated
    # pipeline -> linger_max (fuller groups; the wait is hidden).
    scatter_linger_min_ms: float = 0.2
    scatter_linger_max_ms: float = 8.0
    # Concurrent scatter dispatcher threads: one batch's worker RPC
    # round trip overlaps the next batch's formation.
    scatter_pipeline: int = 2
    # Per-RPC timeout for the batched scatter (covers a worker's NRT
    # commit if an upload landed just before the batch).
    scatter_timeout_s: float = 60.0

    # --- dense retrieval / hybrid fusion (engine/dense.py, ops/dense.py,
    #     cluster/fusion.py) ---
    # Per-doc embedding column beside the sparse postings: populated at
    # ingest by a deterministic embedder, scored on the MXU by a blocked
    # brute-force matmul top-k, fused with the sparse stage at the
    # scatter owner-merge. Disabling drops dense/hybrid query modes
    # (they fail loudly, never silently fall back to sparse).
    embedding_enabled: bool = True
    embedding_dim: int = 64
    # Embedder registry key (engine/embedder.py). "hash" is the hermetic
    # default: signed feature hashing of token STRINGS via blake2b —
    # replica-identical vectors with zero learned weights. Real encoders
    # plug in via register_embedder().
    embedding_model: str = "hash"
    # Doc-axis chunk for the blocked dense kernel (rows per matmul).
    embedding_chunk: int = 1 << 14
    # Default fusion for mode=hybrid when the query doesn't choose:
    # "rrf" (reciprocal-rank, scale-free) | "wsum" (min-max weighted sum).
    fusion_method: str = "rrf"
    fusion_rrf_k: float = 60.0
    fusion_weight_sparse: float = 0.5
    fusion_weight_dense: float = 0.5

    # --- analyzer ---
    lowercase: bool = True
    stopwords: tuple[str, ...] = ()   # Lucene 9 StandardAnalyzer default: none
    max_token_length: int = 255       # StandardAnalyzer.maxTokenLength default

    # --- mesh / parallelism ---
    # "local": single-device engine (ShardIndex/SegmentedIndex layouts).
    # "mesh":  the index lives in ShardedArrays on a ("docs","terms")
    #          device mesh; searches run the distributed shard_map step
    #          (psum global IDF + all_gather top-k) — the serving path
    #          that subsumes the reference's whole worker pool.
    engine_mode: str = "local"         # "local" | "mesh"
    # Mesh index layout: "ell" = blocked-ELL base scored by the
    # compare/MXU kernel + COO append delta (the fast path); "coo" =
    # pure COO scatter scoring (also auto-selected for tfidf_cosine,
    # Lucene parity, and unbounded-results configs, which ELL does not
    # support).
    mesh_layout: str = "ell"           # "ell" | "coo"
    mesh_shape: tuple[int, ...] = ()   # () = all local devices on one "docs" axis
    mesh_axes: tuple[str, ...] = ("docs", "terms")
    # Multi-host bootstrap (jax.distributed over DCN). On TPU pods the
    # coordinator/process values are auto-detected; leave the defaults.
    # Elsewhere set them (or the standard JAX_COORDINATOR_ADDRESS /
    # JAX_NUM_PROCESSES / JAX_PROCESS_ID env vars).
    distributed: bool = False
    dist_coordinator: str = ""         # host:port of process 0
    dist_num_processes: int = 0        # 0 = auto-detect
    dist_process_id: int = -1          # -1 = auto-detect
    query_batch: int = 32              # padded query batch per scoring step
    max_query_terms: int = 32          # padded terms per query
    # In-flight query chunks inside one search_batch call. On small
    # corpora the device step is much shorter than the device->host
    # fetch RTT; depth 2 overlaps one fetch with the next chunk's
    # compute (measured best — deeper only queues serial fetches).
    search_pipeline_depth: int = 2
    # How the three pipeline stages (dispatch / d2h fetch / assemble)
    # execute: "executor" = the shared two-thread PipelineExecutor
    # (chunks from CONCURRENT search calls overlap — the serving-path
    # win on high-RTT device links); "inline" = dispatch-then-drain on
    # the calling thread (per-call overlap only); "auto" = executor on
    # accelerator backends, inline on CPU (where fetches are free and
    # the thread hand-offs are pure overhead).
    search_pipeline_mode: str = "auto"

    # --- capacity bucketing (static shapes for XLA) ---
    min_doc_capacity: int = 1024
    min_nnz_capacity: int = 1 << 16
    min_vocab_capacity: int = 1 << 15

    # --- scoring layout ---
    # "ell": padded rows-by-document, gather/MXU scoring with precomputed
    #        impacts (TPU fast path). "coo": chunked scatter scoring.
    scoring_layout: str = "ell"
    ell_width_cap: int = 256   # max ELL row width; longer docs spill to COO
    # Fused Pallas gather kernel for big ELL blocks (avoids the XLA
    # path's [rows, width, B] HBM materialization — the gather-bound
    # bottleneck at 1M-doc scale). Small blocks always use the XLA path.
    use_pallas: bool = True
    # A-build variant inside the fused kernel (ops/ell.py): "v4"
    # processes two width rows per grid iteration (one accumulate add
    # per pair; i16 packed compares where the vocabulary fits 2^15) —
    # bit-identical scores to "v3", roughly 2/3 the A-build vreg-ops
    # (cost model in BENCH_r09.json; parity matrix in
    # kernel_parity.py). "v3" is the r2-r13 single-row build.
    kernel_a_build: str = "v4"
    # Maintain global df/N/avgdl incrementally on mutation so
    # steady-state commits are O(batch nnz) with the device df advanced
    # by one sparse scatter (segments + mesh-ELL indexes; the
    # df_full_recomputes witness counts the exceptional full passes).
    # False = recompute from the live corpus every commit (the pre-r14
    # control path, kept for bench.py --kernel old-vs-new runs).
    df_incremental: bool = True

    # --- index mode ---
    # "rebuild": every commit re-lays-out the whole corpus (static corpora)
    # "segments": Lucene-style streaming segments — commit is O(new docs),
    #             tombstone deletes, tiered merging above max_segments
    #             (merges with more than sync_merge_nnz postings run on a
    #             background thread, off the commit critical path)
    index_mode: str = "rebuild"
    max_segments: int = 8
    sync_merge_nnz: int = 1 << 20
    # Background merges bound the shared transfer queue to ~one block
    # and, while a commit is concurrently running, additionally sleep
    # pace * (per-block upload time) so the commit's puts interleave
    # instead of queueing behind the merged postings (bounds
    # streaming-commit p99 on shared/tunneled transfer links).
    # 0 disables pacing.
    merge_upload_pace: float = 1.0
    # Concurrent background merges (disjoint size tiers). One merge
    # thread cannot keep up with one new segment per commit at MS MARCO
    # streaming rates; the segment backlog then grows unboundedly.
    merge_workers: int = 2

    # --- tiered postings (engine/tiering.py; segments mode only) ---
    # Device-resident hot set + host/disk cold tier with block-max
    # skipping: segments beyond the HBM budget are evicted to manifested
    # spill dirs (mmap-ed back through the storage seam on fault-in) and
    # most are provably skipped per query batch by per-segment max-score
    # bounds. Off = every segment stays device-resident (pre-tiering
    # behavior). Not supported for tfidf_cosine (no sound bound).
    tier_enabled: bool = False
    # HBM budget for the hot set, in MiB. The budget is SOFT: in-flight
    # searches keep their views alive, and the segment being scored is
    # never evicted from under itself.
    tier_hot_budget_mb: int = 512
    # Relative inflation applied to every block-max upper bound so f32
    # device rounding can never push a true score above the host-side
    # f64 bound (the skip-soundness margin).
    tier_skip_margin: float = 1e-4
    # Upload-ring prefetch depth: how many upcoming cold segments the
    # searcher streams host->HBM ahead of scoring. 2 = double buffering.
    tier_ring_depth: int = 2
    # Cold spill directory. Empty = <index_path>/cold.
    tier_cold_dir: str = ""
    # Autopilot tier policy (requires autopilot_enabled): steers the
    # hot budget toward this tier hit rate — hit rate below target
    # grows the budget, above shrinks it, clamped to the MiB bounds.
    tier_hit_target: float = 0.9
    autopilot_tier_floor_mb: int = 64
    autopilot_tier_ceiling_mb: int = 4096

    # --- storage durability (utils/storage.py) ---
    # fsync-before-ack: an acked upload's raw bytes are fsynced (file +
    # directory, group-committed across concurrent requests) BEFORE the
    # HTTP 200 leaves the worker — the WAL's durability contract
    # applied to the data plane. Off trades the crash window for
    # throughput (tests, ephemeral deployments); atomic-rename publish
    # stays on either way.
    storage_fsync: bool = True
    # Versioned checkpoint dirs retained after a successful publish
    # (the current one plus N-1 fallbacks). Load falls back to the
    # newest INTACT version when the manifest check fails, quarantining
    # the corrupt one — with 1, there is nothing to fall back to.
    storage_keep_versions: int = 2
    # Background integrity-scrub pacing inside the leader's sweep loop
    # (verify placed_docs CRCs against the ledger + the current
    # checkpoint manifest; repair rotten copies from healthy replicas
    # through the anti-entropy machinery). Each pass re-reads the whole
    # store, so the default is minutes, not seconds — real scrubbers
    # run on hour scales. Negative disables; run_integrity_scrub()
    # still works on demand (POST /admin/scrub).
    storage_scrub_ms: float = 600000.0

    # --- checkpoint ---
    # Also store the committed snapshot's device arrays in checkpoints
    # so restore skips the O(corpus) host re-layout (~6x faster restore
    # at 1M docs). Costs one device->host fetch of the snapshot at save
    # time — cheap on real TPU hosts (PCIe), slow over a remote-TPU
    # tunnel whose downlink is ~100x thinner than its uplink. (The
    # segments payload is laid out on host — no device fetch.)
    checkpoint_snapshot_arrays: bool = True
    # Serving-node checkpoints (the reference persists its index on
    # every upload, Worker.java:138). Empty path = <index_path>/checkpoint.
    # interval 0 disables the periodic autosave; /admin/checkpoint
    # triggers one on demand either way. A serve node restores from the
    # checkpoint at boot and then re-walks only documents modified after
    # the save (idempotent upserts keep rebuild-from-documents intact).
    checkpoint_path: str = ""
    checkpoint_interval_s: float = 0.0

    # --- shard recovery (SURVEY §5.3 — capability the reference lacks) ---
    # The leader keeps a durable copy of every document it places (its
    # own documents dir; the reference's leader-local disk is already a
    # download source, Leader.java:112-121) and, when a worker drops out
    # of the registry, re-places that worker's documents onto survivors
    # so the full corpus stays searchable. When the dead worker rejoins
    # (same URL), the leader reconciles by deleting the moved documents
    # from it. Byte recovery covers documents placed during the current
    # leader's tenure; replica OWNERSHIP survives failover through the
    # durable placement map below.
    shard_recovery: bool = True

    # --- replication (R-way placement + failover scatter reads) ---
    # Every uploaded document is placed on this many distinct
    # least-loaded workers (capped by the live worker count). Each
    # scatter assigns exactly ONE live, breaker-closed replica to score
    # each document (the sum-merge stays double-count-free by
    # construction); when that owner fails mid-request the leader
    # re-issues only the orphaned ownership slice to a surviving
    # replica WITHIN the same request, so single-worker death loses no
    # documents. 1 = the pre-replication single-copy behavior
    # (reference parity).
    replication_factor: int = 2
    # Hedged duplicate reads (The Tail at Scale): a worker that has not
    # answered its scatter RPC after this many milliseconds gets its
    # ownership slice speculatively re-issued to the next replica; the
    # merge dedups by owner epoch (the primary's reply wins if it
    # lands). 0 disables hedging.
    scatter_hedge_ms: float = 0.0
    # Debounce for persisting the leader's placement map (doc ->
    # replica set, plus pending-reconcile state) as znodes through the
    # coordination substrate, so a NEW leader resumes with exact
    # ownership instead of an empty in-memory map. Negative disables
    # persistence (per-tenure map only).
    placement_flush_ms: float = 50.0

    # --- elastic rebalancing (cluster/rebalance.py) ---
    # Leader-side live shard migration: the sweep loop detects
    # overloaded shards (doc count above the cluster mean + slack, or
    # above the absolute cap below) and underused capacity (a freshly
    # joined worker far below the mean) and migrates doc ranges live —
    # copy to targets, durably flip ownership through the placement
    # znode, reconcile-delete the old copies. Searches stay exact
    # throughout (per-request owner assignment makes the flip atomic).
    rebalance_enabled: bool = True
    # Absolute per-worker doc-count cap: a shard above it donates docs
    # even when the cluster is otherwise balanced. 0 = no cap
    # (balance-to-mean only).
    rebalance_max_shard_docs: int = 0
    # Self-pacing for the rebalance pass inside the reconcile sweep
    # loop (the sweep interval is the floor). Negative disables the
    # automatic pass; /api/drain and run_once() still work.
    rebalance_sweep_ms: float = 5000.0
    # Self-pacing for the residue anti-entropy pass (ghost/orphan
    # reconciliation of unmapped engine copies left behind by
    # partitions — cluster/placement.py reconcile_residue). Negative
    # disables; run_residue_reconcile() still works on demand.
    residue_sweep_ms: float = 5000.0

    # --- coordination durability + quorum (cluster/wal.py, ensemble.py) ---
    # Empty data dir = in-memory substrate (the pre-durability behavior).
    # Set it and every coordinator write goes through a CRC-framed,
    # fsynced WAL with periodic snapshots; a crashed coordinator
    # restarted on the same dir recovers the full znode tree + sessions.
    coord_data_dir: str = ""
    # This member's id and the full member map ("id=host:port,..."
    # including self). With peers set the coordinator is one member of a
    # Raft-style ensemble: writes are acknowledged only after a majority
    # has them durably, so a 3-member ensemble survives the loss of any
    # one member with zero lost acknowledged writes.
    coord_node_id: str = ""
    coord_peers: str = ""
    # fsync every WAL append before acknowledging (the Raft/ZooKeeper
    # contract). Off trades the crash-tail window for throughput.
    wal_fsync: bool = True
    # Snapshot + compact the WAL every N applied commands.
    wal_snapshot_every: int = 512
    # Election timeout base (randomized 1x-2x per member) and the
    # leader's heartbeat/replication interval; commit timeout bounds how
    # long a write waits for quorum before failing WITHOUT an ack.
    ensemble_election_timeout_s: float = 1.0
    ensemble_heartbeat_s: float = 0.25
    ensemble_commit_timeout_s: float = 5.0

    # --- admission control / overload shedding (cluster/admission.py) ---
    # Master switch for the leader's front-door admission layer
    # (token-bucket rate limiting + queue-depth backpressure on the
    # /leader/* endpoints). Health/metrics endpoints are never
    # admission-controlled regardless.
    admission_enabled: bool = True
    # Per-client sustained admission rate (client id = X-Client-Id
    # header, else peer IP). 0 = unlimited (backpressure still sheds).
    admission_rate_qps: float = 0.0
    # Token-bucket capacity (burst allowance). 0 = 2x admission_rate_qps.
    admission_burst: float = 0.0
    # Backpressure watermarks on the last_scatter_queue_depth gauge
    # (queries left queued after each coalesced batch formed — the same
    # signal the k8s HPA scales workers on): at/above high_water the
    # BULK lane sheds; at/above critical interactive sheds too. 0
    # disables that watermark.
    admission_queue_high_water: int = 128
    admission_queue_critical: int = 512
    # Retry-After hint (seconds) on backpressure sheds (rate-limit
    # sheds compute the honest time-to-next-token instead).
    admission_retry_after_s: float = 0.25
    # Bound on distinct per-client token buckets (LRU-evicted beyond).
    admission_max_clients: int = 4096
    # Weighted-dequeue share of each scatter batch reserved for the
    # bulk lane while interactive traffic is queued (so neither lane
    # can starve the other; interactive always fills first). 0 = bulk
    # rides strictly behind interactive.
    scatter_bulk_share: float = 0.25
    # Leader-side query-result cache entries (LRU), keyed by the
    # df-signature + commit-generation token so any upsert/delete/
    # migration-flip/membership change invalidates — zipfian (skewed-
    # popularity) traffic answers repeats without touching a worker.
    # 0 disables the cache.
    result_cache_entries: int = 1024

    # --- scale-out query plane (cluster/router.py) ---
    # Any-node reads: a NON-leader node serves /leader/start through a
    # read-only follower view of the durable placement znode (watch-
    # refreshed) instead of refusing or falling back to the legacy
    # sum-merge (which double-counts R-replicated documents). Requires
    # placement persistence (placement_flush_ms >= 0); off = the
    # pre-router behavior.
    router_any_node_reads: bool = True
    # Mutation-plane discipline: a non-leader node (and every
    # dedicated router) forwards /leader/upload[-batch] and
    # /leader/delete to the elected leader published at /leader_info —
    # all mutations stay on the leader. Off = serve locally (legacy).
    router_forward_writes: bool = True
    # Periodic placement-view refresh backstop in milliseconds (the
    # data watch on the placement znode is the primary signal; the
    # backstop covers missed watches across coordinator failovers).
    router_refresh_ms: float = 1000.0
    # Honest-staleness threshold: when the follower view has not been
    # confirmed current for this long (coordinator partition), every
    # read response is marked degraded (X-Scatter-Degraded with
    # stale_view=1) and the router's result cache is bypassed until
    # the view self-heals. 0 disables the marker.
    router_stale_ms: float = 5000.0
    # Per-router generation-keyed result-cache entries (LRU), keyed by
    # (membership epoch, placement view version) — every observed
    # placement flush invalidates. 0 disables.
    router_cache_entries: int = 1024

    # --- resilience (cluster plane) ---
    # Leader->worker RPC retry policy: bounded attempts with exponential
    # backoff + jitter; only transient failures (connection-level, 5xx)
    # are retried — see cluster/resilience.py. deadline 0 = attempts-only.
    rpc_max_attempts: int = 3
    rpc_backoff_base_s: float = 0.05
    rpc_backoff_max_s: float = 2.0
    rpc_retry_deadline_s: float = 10.0
    # Per-worker circuit breaker: closed -> open after N consecutive
    # failed logical RPCs -> one half-open probe after reset_s. An open
    # breaker fast-fails scatter/placement calls to that worker (counted
    # as degraded, never as a silent empty merge).
    breaker_failure_threshold: int = 5
    breaker_reset_s: float = 5.0
    # Gray-failure detection: a worker whose SUCCESSFUL-call latency
    # EWMA exceeds this threshold trips its circuit breaker anyway
    # (counted in breaker_slow_trips) — a slow-but-alive worker never
    # fails a call, so consecutive-failure counting would let it drag
    # every scatter it owns to the deadline. 0 disables.
    breaker_slow_threshold_ms: float = 0.0
    # Minimum successful samples in the EWMA before a slow trip may
    # fire (one outlier RPC must not condemn a healthy worker).
    breaker_slow_min_samples: int = 5
    # Periodic leader sweep retrying failed rejoin reconciles
    # (/worker/delete) so moved documents cannot stay double-indexed
    # until the next membership event; pending names are excluded from
    # that worker's merged results meanwhile. 0 disables the sweep.
    reconcile_sweep_interval_s: float = 2.0
    # Transient remote-compile retry: max retries charged per query-batch
    # bucket size; a deterministic compile error (e.g. OOM at a new
    # bucket) stops being retried once the bucket's budget is spent.
    compile_retry_per_bucket: int = 2

    # --- SLO autopilot (cluster/autopilot.py) ---
    # Master kill switch for the leader-side closed-loop controller
    # that tunes the serving knobs (scatter hedge delay, admission
    # watermarks, adaptive-linger ceiling, gray-failure slow-trip
    # threshold) from the live PR-9 histograms. Off = every knob keeps
    # its static config value, exactly as before; flipping it off at
    # runtime (POST /api/autopilot) reverts every managed knob to
    # static INSTANTLY.
    autopilot_enabled: bool = False
    # Control-sweep self-pacing inside the reconcile sweep loop (the
    # sweep interval is the floor). Negative disables the automatic
    # pass; run_once() still works on demand.
    autopilot_interval_ms: float = 2000.0
    # Relative hysteresis dead band: a knob moves only when the sensed
    # target differs from the current value by more than this fraction
    # — the noise filter that makes oscillation structurally hard.
    autopilot_hysteresis: float = 0.15
    # Damping: fraction of the (target - current) error applied per
    # adjustment. 1.0 would jump straight to the target (and ring on
    # noisy sensors); 0.5 converges geometrically.
    autopilot_step: float = 0.5
    # Direction confirmation: a knob moves only after this many
    # CONSECUTIVE sweeps proposed the same direction, so a one-window
    # sensor blip can never reverse an adjustment trend.
    autopilot_confirm: int = 2
    # Bound on the decision-audit ring (GET /api/autopilot).
    autopilot_ring: int = 256
    # Minimum observations a sensor window needs before its controller
    # may act (a 3-sample p95 is noise, not a signal).
    autopilot_min_window: int = 16
    # The one number the operator owns: the admitted-interactive p99
    # target the watermark controller steers toward. Everything else
    # is derived.
    autopilot_p99_slo_ms: float = 600.0
    # Hedge controller: scatter_hedge_ms tracks the windowed scatter-
    # leg p95 plus this epsilon, clamped to [floor, ceiling].
    autopilot_hedge_epsilon_ms: float = 10.0
    autopilot_hedge_floor_ms: float = 5.0
    autopilot_hedge_ceiling_ms: float = 2000.0
    # Watermark controller clamps (admission_queue_high_water; the
    # critical mark keeps the static critical/high ratio).
    autopilot_queue_floor: int = 4
    autopilot_queue_ceiling: int = 8192
    # Linger controller clamps on the adaptive scatter linger CEILING
    # (scatter_linger_max_ms; the floor bound stays static).
    autopilot_linger_floor_ms: float = 1.0
    autopilot_linger_ceiling_ms: float = 50.0
    # Slow-trip controller: breaker_slow_threshold_ms is derived from
    # the cross-worker successful-call latency-EWMA spread (median x
    # this multiple), clamped below.
    autopilot_slow_spread_mult: float = 4.0
    autopilot_slow_floor_ms: float = 50.0
    autopilot_slow_ceiling_ms: float = 5000.0

    # --- observability (utils/tracing.py, utils/metrics.py) ---
    # Bound on the in-process span ring buffer (finished spans kept for
    # GET /api/trace). Appends are GIL-atomic deque ops — the bound is
    # memory, not locking.
    trace_ring_spans: int = 4096
    # Fraction of ROOT traces sampled into the ring (children and
    # remote continuations inherit the decision). 1.0 records every
    # request; 0 disables recording and propagation while keeping trace
    # ids on the local node's log lines (correlation without retention).
    trace_sample_rate: float = 1.0
    # Threshold for the slow-query log: a /leader/start request slower
    # than this logs one warn line carrying its trace id (joinable with
    # /api/trace) and counts in `slow_queries`. 0 disables.
    trace_slow_query_ms: float = 0.0

    # --- wire-protocol versioning (cluster/protover.py) ---
    # Compat-window floor for the data planes (/leader/*, /worker/*): a
    # request declaring a wire-protocol version below this is answered
    # 426 + X-Proto-Rejected: 1 (distinct, non-retryable, never a
    # worker fault). Requests with no version header are implicitly
    # version 1 (the pre-versioning wire), so the default floor keeps
    # old binaries interoperating; raise it only after the whole fleet
    # runs a binary at or above the new floor. Versions ABOVE ours are
    # always accepted (forward compatibility — no ceiling).
    proto_min_compat: int = 1

    # --- traffic capture/replay (utils/storage.py RequestLog) ---
    # Durable request-log path for admitted /leader/start traffic
    # (query + arrival offset + lane + client id), written through the
    # storage seam's CRC-framed append log so a torn tail truncates
    # cleanly instead of corrupting the capture. Empty disables the
    # tap. `bench.py --replay` replays a captured log with original
    # inter-arrival spacing so perf claims run against production-
    # shaped traffic instead of synthetic zipf.
    replay_capture_path: str = ""
    # Bound on captured entries per log (memory- and disk-bounded like
    # the trace ring); the tap stops appending once reached.
    replay_capture_max: int = 100000

    # --- ingest ---
    # C++ tokenize+count+id-map fast path (tfidf_tpu/native); falls back
    # to the pure-Python analyzer when no compiler is available or for
    # non-ASCII documents — results are identical either way.
    native_ingest: bool = True

    # --- compute-plane chaos + degradation (ISSUE 20) ---
    # Gate on the /api/device-nemesis runtime-control endpoint (the
    # scriptable device-fault injector at the JAX dispatch seams,
    # utils/device_nemesis.py). The TFIDF_DEVICE_NEMESIS env var arms
    # rules regardless of this knob — this only exposes the HTTP
    # control surface, which production deployments keep off. Named
    # *_api so the env override (TFIDF_DEVICE_NEMESIS_API) can never
    # collide with the rule-script variable.
    device_nemesis_api: bool = False
    # Host/numpy degraded scoring when the device faults repeatedly:
    # exact same bits as the XLA scoring path (engine/compute_health.py
    # mirrors the pinned-order reductions), honest latency, responses
    # stamped X-Compute-Degraded. Off = faults surface to callers and
    # leader failover is the only recourse.
    compute_fallback: bool = True
    # ComputeHealth state machine: consecutive device faults before the
    # worker reports "degraded" (health surface only) and before it
    # goes "sick" (device dispatch suspended; host fallback serves).
    compute_degraded_after: int = 2
    compute_sick_after: int = 5
    # Seconds between single-probe device retries while sick — the
    # recovery path back to the exact device plane.
    compute_probe_interval_s: float = 5.0
    # Poison-query quarantine (leader/router): a (query, plan)
    # fingerprint is quarantined after compute faults on this many
    # DISTINCT replicas (1 replica = possibly a sick device; 2+ = the
    # query itself is the trigger), then answered 422 +
    # X-Poison-Quarantined without touching workers.
    poison_quarantine_after: int = 2
    # Quarantine entry TTL and LRU bound — poison verdicts expire so a
    # fixed kernel/binary gets a retry, and the table stays bounded.
    poison_quarantine_ttl_s: float = 300.0
    poison_quarantine_max: int = 256
    # OOM backoff ladder floor: an alloc-OOM at batch B retries at B/2,
    # B/4, ... but never below this (at the floor the fallback or the
    # caller takes over) — so one huge batch degrades, not dies.
    oom_backoff_min_batch: int = 8

    # --- misc ---
    log_level: str = "INFO"
    seed: int = 0

    def replace(self, **kw: Any) -> "Config":
        return dataclasses.replace(self, **kw)

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


def _coerce(raw: str, ty: type) -> Any:
    if ty is bool:
        return raw.strip().lower() in ("1", "true", "yes", "on")
    if ty is int:
        return int(raw)
    if ty is float:
        return float(raw)
    if ty is str:
        return raw
    # tuples and anything else: JSON
    val = json.loads(raw)
    return tuple(val) if isinstance(val, list) else val


def load_config(path: str | None = None, env: dict[str, str] | None = None,
                **overrides: Any) -> Config:
    """Build a Config from (lowest to highest precedence): defaults, a JSON
    config file, ``TFIDF_*`` environment variables, keyword overrides."""
    env = os.environ if env is None else env
    values: dict[str, Any] = {}
    if path and os.path.exists(path):
        with open(path) as f:
            loaded = json.load(f)
        for f_ in dataclasses.fields(Config):
            if f_.name in loaded:
                v = loaded[f_.name]
                values[f_.name] = tuple(v) if isinstance(v, list) else v
    for f_ in dataclasses.fields(Config):
        key = _ENV_PREFIX + f_.name.upper()
        if key in env:
            base = Config.__dataclass_fields__[f_.name].default
            ty = type(base) if base is not None and not isinstance(
                base, dataclasses._MISSING_TYPE) else str
            values[f_.name] = _coerce(env[key], ty)
    values.update(overrides)
    return Config(**values)

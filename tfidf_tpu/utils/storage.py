"""Durable-IO seam + scriptable disk nemesis.

The reference's durability unit is the disk: Lucene commits checksummed
segment files and publishes them atomically on every upload
(``Worker.java:138``, PAPER.md §7). Until this module, the framework's
durable surfaces each rolled their own write path — ``save_checkpoint``
wrote straight into the final version dir, the fence sidecar and the
``placed_docs`` store were plain ``open``+``os.replace``, and nothing
outside the coordination WAL carried a checksum — so a torn write, a
flipped bit, or a disk that lies on ``fsync`` could silently change
search results after a restart.

This is the one seam every durable byte now goes through:

- :func:`write_bytes` / :func:`savez` / :func:`read_bytes` /
  :func:`fsync_path` / :func:`fsync_dir` / :func:`replace` — the
  primitive ops, each instrumented with a ``storage.*`` fault point
  (``utils.faults``) AND consulted against the :class:`StorageNemesis`
  rule table, so chaos tests script per-path disk faults without
  monkeypatching a single call site (the disk twin of
  ``cluster/nemesis.py``'s network shim);
- :func:`atomic_write_bytes` / :func:`atomic_write_json` — temp file →
  write → fsync file → atomic rename → fsync dir, the only publish
  discipline a crash cannot tear; the JSON form wraps the payload in a
  CRC32 envelope (legacy un-checksummed files are still readable) so
  bit rot is *detected* instead of silently parsed — a flipped digit
  in a fence epoch parses fine and fences wrong;
- :func:`write_manifest` / :func:`verify_manifest` — a per-directory
  CRC32+size manifest covering every file of a checkpoint version, the
  load-time integrity gate behind checkpoint fallback/quarantine;
- :func:`publish_dir` — build-dir → fsync every file → fsync dir →
  atomic rename into the final versioned name → fsync parent: a
  version directory either exists complete or not at all;
- :class:`GroupCommitter` — cross-thread group commit of fsyncs: the
  fsync-before-ack upload contract without one fsync syscall convoy
  per concurrent request (concurrent commits coalesce into shared
  flush rounds, the coalescer discipline applied to durability);
- :class:`CrcLedger` — name → CRC32 record for a store of raw
  documents (the leader's ``placed_docs``), the reference the
  integrity scrub verifies replicas against;
- :class:`RequestLog` — the durable traffic-capture log (admitted
  ``/leader/start`` queries + arrival offsets + lanes), CRC-framed
  per line so a torn tail truncates cleanly; ``bench.py --replay``
  replays it as production-shaped load.

Nemesis rules are scriptable in-process (``global_storage.arm(...)``)
and via the ``TFIDF_STORAGE_NEMESIS`` env var (a JSON rule list) so
subprocess chaos clusters (``make chaos-powerloss``) boot with the disk
already hostile. Injected faults are real ``OSError`` s with real
``errno`` s (:class:`DiskFault`), so every existing classifier treats
them exactly like the hardware failure they model.
"""

from __future__ import annotations

import errno
import fnmatch
import json
import os
import random
import threading
import time
import zlib

from tfidf_tpu.utils.faults import global_injector
from tfidf_tpu.utils.logging import get_logger
from tfidf_tpu.utils.metrics import global_metrics
from tfidf_tpu.utils.tracing import span_event

log = get_logger("utils.storage")

MANIFEST_NAME = "MANIFEST.json"

# the distinct wire status for a full disk (satellite contract: an
# ENOSPC on upload/checkpoint is an ENVIRONMENT condition — classified
# non-retryable, never a worker fault, never a breaker trip)
STORAGE_FULL_STATUS = 507

# nemesis fault kinds
TORN_WRITE = "torn_write"          # partial bytes land, then EIO
ENOSPC = "enospc"                  # the disk is full
FSYNC_EIO = "fsync_eio"            # fsync reports EIO (fsyncgate)
BITROT = "bitrot"                  # read-back returns flipped bytes
CRASH_BEFORE_RENAME = "crash_before_rename"   # die before the publish
CRASH_AFTER_RENAME = "crash_after_rename"     # die after it

_KINDS = (TORN_WRITE, ENOSPC, FSYNC_EIO, BITROT,
          CRASH_BEFORE_RENAME, CRASH_AFTER_RENAME)

# op → kinds that fire there
_OP_KINDS = {
    "write": (TORN_WRITE, ENOSPC),
    "fsync": (FSYNC_EIO,),
    "read": (BITROT,),
    "rename": (CRASH_BEFORE_RENAME, CRASH_AFTER_RENAME),
}


class StorageCorruption(ValueError):
    """A durable file failed its integrity check (CRC/size/manifest).
    A ``ValueError`` subclass on purpose: every existing
    unreadable-state handler (``wal.load``, ``FenceGuard.__init__``)
    already catches ``ValueError`` and falls back loudly."""


class DiskFault(OSError):
    """An injected disk fault. A real ``OSError`` with a real
    ``errno`` — callers classify it exactly like the hardware failure
    it models (EIO, ENOSPC)."""


class _SRule:
    __slots__ = ("rid", "kind", "glob", "probability", "remaining",
                 "keep_bytes")

    def __init__(self, rid: int, kind: str, glob: str,
                 probability: float, times: int | None,
                 keep_bytes: int) -> None:
        self.rid = rid
        self.kind = kind
        self.glob = glob
        self.probability = probability
        self.remaining = times
        self.keep_bytes = keep_bytes


class StorageNemesis:
    """The scripted disk-fault plan (rule-driven like
    ``cluster.nemesis.NemesisNet``). Rules match ``(op, path)``: the
    op is the seam primitive (write / fsync / read / rename — implied
    by the rule's fault kind) and the path matches an ``fnmatch`` glob
    against the absolute path, so one plan can target exactly
    ``*/docs.npz`` or a whole node's index dir."""

    def __init__(self, seed: int = 0) -> None:
        self._lock = threading.Lock()
        self._rules: tuple[_SRule, ...] = ()
        self._next_id = 1
        self._rng = random.Random(seed)
        self.fired: dict[str, int] = {}

    def arm(self, kind: str, path_glob: str = "*",
            probability: float = 1.0, times: int | None = None,
            keep_bytes: int = 0) -> int:
        if kind not in _KINDS:
            raise ValueError(f"unknown storage fault kind {kind!r} "
                             f"(choose from {_KINDS})")
        with self._lock:
            rid = self._next_id
            self._next_id += 1
            self._rules = self._rules + (_SRule(
                rid, kind, path_glob, probability, times, keep_bytes),)
        log.info("storage nemesis rule armed", kind=kind, glob=path_glob,
                 rule=rid)
        return rid

    def remove(self, rid: int) -> None:
        with self._lock:
            self._rules = tuple(r for r in self._rules if r.rid != rid)

    def heal(self) -> None:
        with self._lock:
            n = len(self._rules)
            self._rules = ()
        if n:
            log.info("storage nemesis healed", rules_cleared=n)

    def active(self) -> bool:
        return bool(self._rules)

    def load_env(self, raw: str | None = None) -> int:
        """Arm rules from a JSON list (the ``TFIDF_STORAGE_NEMESIS``
        env var): ``[{"kind": "torn_write", "glob": "*docs*",
        "probability": 0.1, "times": 3, "keep_bytes": 8}, ...]``.
        Returns the number of rules armed."""
        raw = os.environ.get("TFIDF_STORAGE_NEMESIS") \
            if raw is None else raw
        if not raw:
            return 0
        rules = json.loads(raw)
        for r in rules:
            self.arm(r["kind"], r.get("glob", "*"),
                     probability=float(r.get("probability", 1.0)),
                     times=r.get("times"),
                     keep_bytes=int(r.get("keep_bytes", 0)))
        return len(rules)

    def match(self, op: str, path: str) -> _SRule | None:
        """One firing rule for this (op, path), or None. Decrements
        bounded rules and counts the fire (visible in traces like every
        ``FaultInjector`` fire — the chaos run's audit trail)."""
        rules = self._rules
        if not rules:
            return None
        kinds = _OP_KINDS[op]
        ap = os.path.abspath(path)
        with self._lock:
            for r in rules:
                if r.kind not in kinds:
                    continue
                if not fnmatch.fnmatch(ap, r.glob):
                    continue
                if r.remaining is not None and r.remaining <= 0:
                    continue
                if r.probability < 1.0 \
                        and self._rng.random() > r.probability:
                    continue
                if r.remaining is not None:
                    r.remaining -= 1
                self.fired[r.kind] = self.fired.get(r.kind, 0) + 1
                span_event("storage_fault_injected", kind=r.kind,
                           path=os.path.basename(ap))
                global_metrics.inc("storage_faults_injected")
                return r
        return None


# Process-wide nemesis used by the seam primitives; tests script it,
# subprocess chaos clusters arm it from TFIDF_STORAGE_NEMESIS at import.
global_storage = StorageNemesis()
if os.environ.get("TFIDF_STORAGE_NEMESIS"):
    global_storage.load_env()


def _enospc_seen(e: BaseException) -> None:
    """Count every observed disk-full, real or injected — the
    ``storage_enospc`` counter the 507 wire contract is audited by."""
    if isinstance(e, OSError) and e.errno == errno.ENOSPC:
        global_metrics.inc("storage_enospc")


def is_enospc(e: BaseException) -> bool:
    return isinstance(e, OSError) and e.errno == errno.ENOSPC


# ---------------------------------------------------------------------------
# seam primitives
# ---------------------------------------------------------------------------

def write_bytes(path: str, data: bytes) -> None:
    """Write ``data`` to ``path`` (no atomicity — callers write into a
    temp name or a build dir and publish via :func:`replace` /
    :func:`publish_dir`). The torn-write / ENOSPC injection site."""
    global_injector.check("storage.write")
    rule = global_storage.match("write", path)
    if rule is not None and rule.kind == ENOSPC:
        e = DiskFault(errno.ENOSPC, "injected: no space left on device",
                      path)
        _enospc_seen(e)
        raise e
    try:
        with open(path, "wb") as f:
            if rule is not None:   # TORN_WRITE: partial bytes then EIO
                f.write(data[:max(0, rule.keep_bytes)])
                f.flush()
                raise DiskFault(errno.EIO, "injected: torn write", path)
            f.write(data)
    except OSError as e:
        _enospc_seen(e)
        raise


def savez(path: str, **arrays) -> None:
    """``np.savez`` through the seam (the checkpoint array files).
    Torn-write rules truncate the finished archive to ``keep_bytes``
    before raising — exactly the half-written .npz a crash leaves."""
    import numpy as np
    global_injector.check("storage.write")
    rule = global_storage.match("write", path)
    if rule is not None and rule.kind == ENOSPC:
        e = DiskFault(errno.ENOSPC, "injected: no space left on device",
                      path)
        _enospc_seen(e)
        raise e
    try:
        # via an open handle: np.savez APPENDS ".npz" to a bare path,
        # which would silently rename temp files out from under callers
        with open(path, "wb") as fh:
            np.savez(fh, **arrays)
    except OSError as e:
        _enospc_seen(e)
        raise
    if rule is not None:   # TORN_WRITE
        with open(path, "r+b") as f:
            f.truncate(max(0, rule.keep_bytes))
        raise DiskFault(errno.EIO, "injected: torn write", path)


def read_bytes(path: str) -> bytes:
    """Read a durable file through the seam — the bit-rot injection
    site: a matching rule returns silently damaged bytes, which only a
    checksum (manifest / JSON envelope) can catch."""
    global_injector.check("storage.read")
    with open(path, "rb") as f:
        data = f.read()
    rule = global_storage.match("read", path)
    if rule is not None and data:   # BITROT: flip a deterministic byte
        i = rule.keep_bytes % len(data)
        data = data[:i] + bytes([data[i] ^ 0x5A]) + data[i + 1:]
    return data


def read_memmap(path: str, dtype, shape: tuple):
    """Map a durable array file read-only through the seam — the cold
    postings tier (``engine/tiering.py``): the OS page cache IS the
    host-RAM tier, so a fault-in touches only the pages the device
    upload actually streams. Integrity is the caller's manifest gate
    (``verify_manifest`` BEFORE mapping — its ``file_crc`` pass is a
    read-seam site, so armed bit rot is detected there); a rule that
    matches here anyway degrades the map to a damaged in-memory copy,
    keeping the chaos contract (injected rot is observable, never
    silently bypassed) even for callers that skip the gate."""
    import numpy as np
    global_injector.check("storage.read")
    mm = np.memmap(path, dtype=dtype, mode="r", shape=shape)
    rule = global_storage.match("read", path)
    if rule is not None and mm.size:
        buf = np.array(mm)          # materialize, then flip one byte
        flat = buf.view(np.uint8).reshape(-1)
        flat[rule.keep_bytes % flat.shape[0]] ^= 0x5A
        return buf
    return mm


def fsync_path(path: str) -> None:
    """fsync one file's data. The fsync-EIO injection site."""
    global_injector.check("storage.fsync")
    if global_storage.match("fsync", path) is not None:
        raise DiskFault(errno.EIO, "injected: fsync failed", path)
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
    global_metrics.inc("storage_fsyncs")


def fsync_dir(path: str) -> None:
    """fsync a directory so a rename into it survives power loss."""
    global_injector.check("storage.fsync")
    if global_storage.match("fsync", path) is not None:
        raise DiskFault(errno.EIO, "injected: fsync failed", path)
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return   # platform without directory fds
    try:
        os.fsync(fd)
    except OSError:
        pass     # some filesystems refuse dir fsync; rename is best-effort
    finally:
        os.close(fd)
    global_metrics.inc("storage_fsyncs")


def replace(src: str, dst: str) -> None:
    """Atomic rename through the seam — the crash-before/after-rename
    injection window of every publish."""
    global_injector.check("storage.rename")
    rule = global_storage.match("rename", dst)
    if rule is not None and rule.kind == CRASH_BEFORE_RENAME:
        raise DiskFault(errno.EIO, "injected: crash before rename", dst)
    try:
        os.replace(src, dst)
    except OSError as e:
        _enospc_seen(e)
        raise
    if rule is not None:   # CRASH_AFTER_RENAME
        raise DiskFault(errno.EIO, "injected: crash after rename", dst)


# ---------------------------------------------------------------------------
# atomic publish
# ---------------------------------------------------------------------------

def atomic_write_bytes(path: str, data: bytes, fsync: bool = True,
                       dirsync: bool = True) -> None:
    """The crash-consistent single-file publish: unique temp → write →
    fsync file → atomic rename → fsync dir. At every instant ``path``
    holds either the old complete content or the new complete content;
    with ``fsync`` the new content survives power loss once this
    returns."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
    try:
        write_bytes(tmp, data)
        if fsync:
            fsync_path(tmp)
        replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass
    if fsync and dirsync:
        fsync_dir(d)


def _envelope(obj) -> bytes:
    body = json.dumps(obj, separators=(",", ":"), sort_keys=True)
    return json.dumps({"crc": zlib.crc32(body.encode("utf-8")),
                       "payload": obj},
                      separators=(",", ":"), sort_keys=True).encode()


def atomic_write_json(path: str, obj, fsync: bool = True) -> None:
    """Atomic, *checksummed* JSON publish: the payload is wrapped in a
    CRC32 envelope so bit rot is detected at read time instead of being
    silently parsed (a flipped digit in an epoch or an offset is valid
    JSON with wrong meaning)."""
    atomic_write_bytes(path, _envelope(obj), fsync=fsync)


def read_json(path: str):
    """Read a JSON file written by :func:`atomic_write_json`, verifying
    its CRC envelope (:class:`StorageCorruption` on mismatch). Legacy
    files without an envelope are returned as-is — pre-seam sidecars
    stay readable across the upgrade."""
    raw = read_bytes(path)
    obj = json.loads(raw.decode("utf-8"))
    if isinstance(obj, dict) and set(obj) == {"crc", "payload"}:
        body = json.dumps(obj["payload"], separators=(",", ":"),
                          sort_keys=True)
        if zlib.crc32(body.encode("utf-8")) != obj["crc"]:
            global_metrics.inc("storage_corruptions_detected")
            raise StorageCorruption(f"CRC mismatch in {path}")
        return obj["payload"]
    return obj


# ---------------------------------------------------------------------------
# directory manifests + versioned publish
# ---------------------------------------------------------------------------

def write_manifest(dirpath: str, fsync: bool = True) -> dict:
    """Write ``MANIFEST.json`` covering every regular file in
    ``dirpath`` (CRC32 + size each). The manifest itself is a
    checksummed atomic JSON file; together with :func:`publish_dir`
    this makes a version directory self-verifying."""
    files: dict[str, dict] = {}
    for name in sorted(os.listdir(dirpath)):
        full = os.path.join(dirpath, name)
        if name == MANIFEST_NAME or not os.path.isfile(full):
            continue
        files[name] = {"crc": file_crc(full),
                       "size": os.path.getsize(full)}
    manifest = {"files": files}
    atomic_write_json(os.path.join(dirpath, MANIFEST_NAME), manifest,
                      fsync=fsync)
    return manifest


def verify_manifest(dirpath: str) -> list[str]:
    """Integrity-check a version directory against its manifest.
    Returns a list of human-readable problems — empty means intact.
    A missing or unreadable manifest is itself a problem: an
    unverifiable checkpoint must never be silently trusted."""
    mpath = os.path.join(dirpath, MANIFEST_NAME)
    if not os.path.exists(mpath):
        return [f"manifest missing: {mpath}"]
    try:
        manifest = read_json(mpath)
    except (ValueError, OSError) as e:
        return [f"manifest unreadable: {e!r}"]
    files = manifest.get("files")
    if not isinstance(files, dict):
        return ["manifest malformed: no files map"]
    problems: list[str] = []
    for name, want in sorted(files.items()):
        full = os.path.join(dirpath, name)
        if not os.path.isfile(full):
            problems.append(f"{name}: missing")
            continue
        size = os.path.getsize(full)
        if size != want.get("size"):
            problems.append(f"{name}: size {size} != "
                            f"{want.get('size')} (truncated?)")
        elif file_crc(full) != want.get("crc"):
            problems.append(f"{name}: CRC mismatch (bit rot?)")
    if problems:
        global_metrics.inc("storage_corruptions_detected")
    return problems


def publish_dir(build_dir: str, final_dir: str) -> None:
    """Atomically publish a fully-built directory under its final
    versioned name: fsync every file, fsync the build dir, rename, and
    fsync the parent. A crash anywhere leaves either no ``final_dir``
    at all or a complete one — the newest version can never be the
    torn one."""
    for name in sorted(os.listdir(build_dir)):
        full = os.path.join(build_dir, name)
        if os.path.isfile(full):
            fsync_path(full)
    fsync_dir(build_dir)
    if os.path.exists(final_dir):
        import shutil
        shutil.rmtree(final_dir)   # stale remnant of a failed publish
    replace(build_dir, final_dir)
    fsync_dir(os.path.dirname(os.path.abspath(final_dir)) or ".")


def file_crc(path: str) -> int:
    """Incremental CRC32 of a file's current bytes, chunked so a
    GB-scale checkpoint array never materializes in memory (zlib.crc32
    is streaming). Still a read-seam site: an armed bit-rot rule flips
    a byte in the stream exactly as on real hardware, where the
    scrubber reads the same rotting platter."""
    global_injector.check("storage.read")
    rule = global_storage.match("read", path)
    crc = 0
    first = True
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            if rule is not None and first:
                i = rule.keep_bytes % len(chunk)
                chunk = chunk[:i] + bytes([chunk[i] ^ 0x5A]) \
                    + chunk[i + 1:]
            first = False
            crc = zlib.crc32(chunk, crc)
    return crc


# ---------------------------------------------------------------------------
# group commit
# ---------------------------------------------------------------------------

class GroupCommitter:
    """Cross-thread group commit of fsyncs — the fsync-before-ack
    upload contract without one fsync convoy per request.

    ``sync(paths)`` blocks until every path in ``paths`` has been
    fsynced by SOME flush round that started after the call. Concurrent
    callers coalesce: the first becomes the flusher and drains the
    queue (deduplicating paths — N uploads into one directory cost one
    dir fsync per round, not N); later arrivals wait on their round's
    event. The discipline is the WAL's fsync-before-ack applied to raw
    document bytes, batched the way the query coalescer batches
    scoring."""

    # fan-out width for one flush round: os.fsync releases the GIL and
    # the kernel can retire journal flushes for independent files
    # concurrently, so a wide round is bounded by the slowest flush,
    # not the sum
    _FANOUT = 8

    def __init__(self, enabled: bool = True) -> None:
        self.enabled = enabled
        self._lock = threading.Lock()
        self._pending: list[tuple[list[str], threading.Event,
                                  list[BaseException]]] = []
        self._flushing = False
        self._pool = None   # lazy: most processes never group-commit

    def sync(self, paths: list[str]) -> None:
        if not self.enabled or not paths:
            return
        ev = threading.Event()
        errs: list[BaseException] = []
        with self._lock:
            self._pending.append((list(paths), ev, errs))
            if self._flushing:
                flusher = False
            else:
                self._flushing = True
                flusher = True
        if flusher:
            self._flush_rounds()
        # bounded-slice wait + takeover (graftcheck indefinite-wait
        # audit): if the current flusher thread dies abnormally before
        # draining this entry, the waiter becomes the flusher itself —
        # a commit can be slow (the disk), never wedged forever
        while not ev.wait(timeout=0.5):
            takeover = False
            with self._lock:
                if not self._flushing and not ev.is_set():
                    self._flushing = True
                    takeover = True
            if takeover:
                self._flush_rounds()
        if errs:
            raise errs[0]

    def _flush_rounds(self) -> None:
        try:
            self._flush_rounds_inner()
        except BaseException:
            # an abnormal escape (per-path errors are already caught)
            # must not leave _flushing latched — waiters take over
            with self._lock:
                self._flushing = False
            raise

    def _flush_rounds_inner(self) -> None:
        while True:
            with self._lock:
                batch = self._pending
                self._pending = []
                if not batch:
                    self._flushing = False
                    return
            try:
                self._flush_one_round(batch)
            except BaseException as e:
                # a popped batch's waiters are unreachable by the
                # takeover loop (they left _pending) — fail them loudly
                # before re-raising, or their sync() calls spin forever
                err = e if isinstance(e, Exception) \
                    else RuntimeError(f"group commit died: {e!r}")
                for _paths, ev, errs in batch:
                    if not ev.is_set():
                        errs.append(err)
                        ev.set()
                raise

    def _flush_one_round(self, batch) -> None:
        unique: dict[str, BaseException | None] = {}
        for paths, _ev, _errs in batch:
            for p in paths:
                unique.setdefault(p, None)

        def flush_one(p: str) -> None:
            try:
                if os.path.isdir(p):
                    fsync_dir(p)
                else:
                    fsync_path(p)
            except Exception as e:   # noqa: BLE001 — per-path verdict
                _enospc_seen(e)
                unique[p] = e

        if len(unique) > 1:
            if self._pool is None:
                from concurrent.futures import ThreadPoolExecutor
                self._pool = ThreadPoolExecutor(
                    max_workers=self._FANOUT,
                    thread_name_prefix="group-commit")
            list(self._pool.map(flush_one, unique))
        else:
            for p in unique:
                flush_one(p)
        global_metrics.inc("storage_group_commits")
        global_metrics.inc("storage_group_commit_items", len(batch))
        for paths, ev, errs in batch:
            for p in paths:
                e = unique.get(p)
                if e is not None:
                    errs.append(e)
            ev.set()


# Process-wide committer shared by every engine/node in the process —
# exactly the sharing that makes group commit pay: concurrent upload
# handler threads (even across in-process test nodes) coalesce.
global_committer = GroupCommitter()


# ---------------------------------------------------------------------------
# CRC ledger (integrity-scrub reference)
# ---------------------------------------------------------------------------

class CrcLedger:
    """name → CRC32 of a raw-document store, persisted as a checksummed
    atomic JSON file. The integrity scrub verifies the store's current
    bytes against this record — without an independent record, bit rot
    in a stored document is undetectable (the bytes are their own only
    witness). Flushes are debounced by the caller (the sweep loop);
    entries recorded after the last flush are simply unverifiable until
    the next one, which the scrub skips rather than guesses about."""

    def __init__(self, path: str) -> None:
        self._path = path
        self._lock = threading.Lock()
        self._dirty = False
        self._map: dict[str, int] = {}
        try:
            if os.path.exists(path):
                got = read_json(path)
                if isinstance(got, dict):
                    self._map = {str(k): int(v) for k, v in got.items()}
        except (ValueError, OSError) as e:
            # an unreadable ledger means nothing can be verified until
            # re-recorded — loud, never fatal (the store itself is fine)
            log.warning("crc ledger unreadable; starting empty",
                        path=path, err=repr(e))

    def record(self, name: str, crc: int) -> None:
        with self._lock:
            self._map[name] = crc
            self._dirty = True

    def forget(self, name: str) -> None:
        with self._lock:
            if self._map.pop(name, None) is not None:
                self._dirty = True

    def get(self, name: str) -> int | None:
        with self._lock:
            return self._map.get(name)

    def names(self) -> list[str]:
        with self._lock:
            return list(self._map)

    def flush(self, fsync: bool = True) -> bool:
        with self._lock:
            if not self._dirty:
                return False
            snapshot = dict(self._map)
            self._dirty = False
        try:
            atomic_write_json(self._path, snapshot, fsync=fsync)
        except OSError as e:
            with self._lock:
                self._dirty = True   # retry at the next flush
            log.warning("crc ledger flush failed", err=repr(e))
            return False
        return True


# ---------------------------------------------------------------------------
# traffic capture — the replayable request log
# ---------------------------------------------------------------------------

class RequestLog:
    """Durable, replayable capture of front-door search traffic: one
    record per ADMITTED ``/leader/start`` request — query text, arrival
    offset (monotonic seconds since the log opened), admission lane,
    and client id — so perf claims can replay production-shaped
    traffic instead of synthetic zipf (``bench.py --replay``).

    Framing is the WAL's discipline applied to capture: each record is
    one ``<crc32-hex> <compact-json>\\n`` line over an append handle
    held by this class (the capture log IS the seam for its own
    CRC-framed lines, the ``cluster/wal.py`` precedent — pinned in the
    graftcheck storageseam allowlist), and :meth:`read` stops at the
    first frame whose CRC fails, so a torn tail (or injected bit rot —
    reads go through :func:`read_bytes`) truncates cleanly instead of
    replaying a damaged query. Appends are buffered with a periodic
    flush; :meth:`flush`/:meth:`close` drive the buffered tail through
    the same fsync fault point the rest of the seam uses. Capture is an
    observability artifact, not acked state — flush-on-close is the
    durability contract, not fsync-before-ack."""

    _FLUSH_EVERY = 256

    def __init__(self, path: str, max_entries: int = 100000) -> None:
        self._path = path
        self._lock = threading.Lock()
        self._max = max(0, int(max_entries))
        self._count = 0
        self._t0 = time.monotonic()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(path, "ab")

    @property
    def path(self) -> str:
        return self._path

    def record(self, query: str, lane: str, client: str = "") -> bool:
        """Append one admitted request; False once the entry bound is
        reached (bounded like the trace ring) or the log is closed."""
        line = json.dumps(
            {"t": round(time.monotonic() - self._t0, 6),
             "query": query, "lane": lane, "client": client},
            separators=(",", ":")).encode("utf-8")
        framed = b"%08x %s\n" % (zlib.crc32(line) & 0xFFFFFFFF, line)
        with self._lock:
            if self._f is None or self._count >= self._max:
                return False
            self._count += 1
            try:
                self._f.write(framed)
                if self._count % self._FLUSH_EVERY == 0:
                    self._f.flush()
            except OSError as e:
                _enospc_seen(e)
                log.warning("request-log append failed", err=repr(e))
                return False
        global_metrics.inc("capture_records")
        return True

    def flush(self, fsync: bool = True) -> None:
        """Drive the buffered tail to disk (the fsync-EIO fault point,
        like every seam fsync)."""
        with self._lock:
            if self._f is None:
                return
            self._f.flush()
            if fsync:
                global_injector.check("storage.fsync")
                if global_storage.match("fsync", self._path) is not None:
                    raise DiskFault(errno.EIO, "injected: fsync failed",
                                    self._path)
                os.fsync(self._f.fileno())
                global_metrics.inc("storage_fsyncs")

    def close(self) -> None:
        try:
            self.flush(fsync=True)
        except OSError as e:
            log.warning("request-log flush-on-close failed", err=repr(e))
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None

    @staticmethod
    def read(path: str) -> list[dict]:
        """Decode a captured log: every intact record in arrival order,
        truncated cleanly at the first frame whose CRC fails (torn
        tail / bit rot — reads go through the seam, so the disk
        nemesis can damage them and this contract is testable)."""
        out: list[dict] = []
        for line in read_bytes(path).splitlines():
            if not line.strip():
                continue
            try:
                crc_hex, payload = line.split(b" ", 1)
                if int(crc_hex, 16) != (zlib.crc32(payload) & 0xFFFFFFFF):
                    break
                out.append(json.loads(payload.decode("utf-8")))
            except ValueError:
                break
        return out

// Native ingest hot path: tokenizer + vocabulary + per-doc TF builder.
//
// The reference delegates this work to Lucene's analysis chain inside the
// JVM (StandardAnalyzer, Worker.java:71-73); here it is the host-side
// bottleneck feeding the TPU (text -> sorted (term id, tf) arrays), so it
// is native C++ behind a C ABI consumed via ctypes
// (tfidf_tpu/native/__init__.py).
//
// Scope: the ASCII fast path of the Python analyzer
// (tfidf_tpu/ops/analyzer.py) with BIT-IDENTICAL tokenization; documents
// containing non-ASCII bytes are rejected with TFIDF_NONASCII and the
// caller falls back to the (Unicode-complete) Python chain against the
// SAME vocabulary handle, so results are independent of which path ran.
//
// Tokenizer rules replicated exactly (see _TOKEN_RE in ops/analyzer.py):
//   - at a digit: digits, optionally extended by ('.'|',')digits groups
//     ("3.14", "1,000"; "3abc" -> "3","abc" — the digit branch wins and
//     letters do NOT extend it);
//   - at a letter/underscore: [A-Za-z0-9_]+ runs, optionally extended by
//     '<apostrophe>word' groups ("can't");
//   - lowercase filter, stopword filter, and >max_token_length splitting
//     applied in the same order as the Python chain.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

extern "C" {

#define TFIDF_NONASCII (-2)
#define TFIDF_OVERFLOW (-1)
#define TFIDF_BADID (-3)

struct Engine {
    // vocabulary: term -> dense id, append-only, first-seen order
    std::unordered_map<std::string, int32_t> ids;
    std::vector<std::string> terms;
    // analyzer params
    std::unordered_set<std::string> stopwords;
    int lowercase = 1;
    int64_t max_token_len = 255;
    // scratch (reused across calls; one Engine per Python engine, used
    // under the ingest lock, so no concurrency here)
    std::unordered_map<int32_t, float> doc_counts;
    std::vector<std::pair<int32_t, float>> sorted;
};

Engine* tfidf_engine_new(int lowercase, int64_t max_token_len,
                         const char* stops, int64_t stops_len) {
    Engine* e = new Engine();
    e->lowercase = lowercase;
    e->max_token_len = max_token_len;
    // stopwords arrive newline-joined
    int64_t start = 0;
    for (int64_t i = 0; i <= stops_len; ++i) {
        if (i == stops_len || stops[i] == '\n') {
            if (i > start)
                e->stopwords.emplace(stops + start, i - start);
            start = i + 1;
        }
    }
    return e;
}

void tfidf_engine_free(Engine* e) { delete e; }

int64_t tfidf_vocab_size(const Engine* e) {
    return (int64_t)e->terms.size();
}

// term -> id; add=0 returns -1 for unknown terms
int32_t tfidf_vocab_lookup(Engine* e, const char* tok, int64_t len,
                           int add) {
    std::string key(tok, (size_t)len);
    auto it = e->ids.find(key);
    if (it != e->ids.end()) return it->second;
    if (!add) return -1;
    int32_t tid = (int32_t)e->terms.size();
    e->ids.emplace(std::move(key), tid);
    e->terms.emplace_back(tok, (size_t)len);
    return tid;
}

// id -> term (for checkpoints / debugging); returns length, or
// TFIDF_BADID / TFIDF_OVERFLOW
int64_t tfidf_vocab_term(const Engine* e, int32_t tid, char* buf,
                         int64_t cap) {
    if (tid < 0 || (size_t)tid >= e->terms.size()) return TFIDF_BADID;
    const std::string& t = e->terms[(size_t)tid];
    if ((int64_t)t.size() > cap) return TFIDF_OVERFLOW;
    std::memcpy(buf, t.data(), t.size());
    return (int64_t)t.size();
}

// all terms, newline-joined, in id order; returns bytes written or -1 if
// the buffer is too small (call tfidf_vocab_dump_size first)
int64_t tfidf_vocab_dump_size(const Engine* e) {
    int64_t n = 0;
    for (const auto& t : e->terms) n += (int64_t)t.size() + 1;
    return n;
}

int64_t tfidf_vocab_dump(const Engine* e, char* buf, int64_t cap) {
    int64_t pos = 0;
    for (const auto& t : e->terms) {
        if (pos + (int64_t)t.size() + 1 > cap) return TFIDF_OVERFLOW;
        std::memcpy(buf + pos, t.data(), t.size());
        pos += (int64_t)t.size();
        buf[pos++] = '\n';
    }
    return pos;
}

static inline bool is_word(unsigned char c) {
    return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
           (c >= '0' && c <= '9') || c == '_';
}
static inline bool is_digit(unsigned char c) {
    return c >= '0' && c <= '9';
}

// Analyze one ASCII document: tokenize+filter+count+map in one pass.
// Fills out_ids/out_tfs (sorted by id) up to `cap` entries.
// Returns the number of distinct terms, TFIDF_OVERFLOW if cap is too
// small, or TFIDF_NONASCII if the text has non-ASCII bytes (caller must
// use the Python analyzer). *out_len receives the kept-token count (the
// document length for BM25).
int64_t tfidf_analyze_doc(Engine* e, const char* text, int64_t len,
                          int add, int32_t* out_ids, float* out_tfs,
                          int64_t cap, double* out_len) {
    for (int64_t i = 0; i < len; ++i)
        if ((unsigned char)text[i] >= 0x80) return TFIDF_NONASCII;

    auto& counts = e->doc_counts;
    counts.clear();
    double total = 0.0;
    std::string tok;
    const bool lower = e->lowercase != 0;
    const int64_t maxlen = e->max_token_len;
    const bool has_stops = !e->stopwords.empty();

    auto emit = [&](const char* s, int64_t n) {
        tok.assign(s, (size_t)n);
        if (lower)
            for (auto& ch : tok)
                if (ch >= 'A' && ch <= 'Z') ch += 32;
        // overlong tokens are split into maxlen pieces (StandardTokenizer
        // behavior), each filtered independently — same as the Python chain
        for (size_t off = 0; off < tok.size(); off += (size_t)maxlen) {
            std::string piece = tok.substr(off, (size_t)maxlen);
            if (piece.empty()) continue;
            if (has_stops && e->stopwords.count(piece)) continue;
            int32_t tid;
            if (add) {
                tid = tfidf_vocab_lookup(e, piece.data(),
                                         (int64_t)piece.size(), 1);
            } else {
                auto it = e->ids.find(piece);
                if (it == e->ids.end()) continue;
                tid = it->second;
            }
            counts[tid] += 1.0f;
            total += 1.0;
        }
    };

    int64_t i = 0;
    while (i < len) {
        unsigned char c = (unsigned char)text[i];
        if (is_digit(c)) {
            int64_t start = i;
            while (i < len && is_digit((unsigned char)text[i])) ++i;
            // (?:[.,]\d+)* extensions
            while (i + 1 < len &&
                   (text[i] == '.' || text[i] == ',') &&
                   is_digit((unsigned char)text[i + 1])) {
                ++i;
                while (i < len && is_digit((unsigned char)text[i])) ++i;
            }
            emit(text + start, i - start);
        } else if (is_word(c)) {
            int64_t start = i;
            while (i < len && is_word((unsigned char)text[i])) ++i;
            // (?:'\w+)* extensions (ASCII apostrophe only; '’' is non-ASCII)
            while (i + 1 < len && text[i] == '\'' &&
                   is_word((unsigned char)text[i + 1])) {
                ++i;
                while (i < len && is_word((unsigned char)text[i])) ++i;
            }
            emit(text + start, i - start);
        } else {
            ++i;
        }
    }

    if ((int64_t)counts.size() > cap) return TFIDF_OVERFLOW;
    auto& sorted = e->sorted;
    sorted.assign(counts.begin(), counts.end());
    std::sort(sorted.begin(), sorted.end());
    int64_t n = 0;
    for (const auto& kv : sorted) {
        out_ids[n] = kv.first;
        out_tfs[n] = kv.second;
        ++n;
    }
    *out_len = total;
    return n;
}

}  // extern "C"

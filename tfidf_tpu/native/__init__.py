"""ctypes bindings + build-on-demand for the native ingest hot path.

The shared library is compiled from ``tfidf_native.cpp`` with the system
g++ on first use (cached next to the source; rebuilt when the source is
newer). Everything degrades gracefully: if no compiler is available the
framework runs on the pure-Python analyzer with identical results —
:func:`available` is the capability probe.

Binding layer only; the analysis semantics live in the C++ (and are
pinned by parity tests against the Python chain in tests/test_native.py).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from tfidf_tpu.utils.logging import get_logger

log = get_logger("native")

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "tfidf_native.cpp")
_LIB = os.path.join(_HERE, "libtfidf_native.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _build() -> bool:
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", _SRC,
           "-o", _LIB + ".tmp"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError) as e:
        log.warning("native build failed; using pure-Python analyzer",
                    err=repr(e))
        return False
    os.replace(_LIB + ".tmp", _LIB)
    log.info("native library built", path=_LIB)
    return True


def _load() -> ctypes.CDLL | None:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        stale = (not os.path.exists(_LIB)
                 or os.path.getmtime(_LIB) < os.path.getmtime(_SRC))
        if stale and not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError as e:
            log.warning("native library load failed", err=repr(e))
            return None
        lib.tfidf_engine_new.restype = ctypes.c_void_p
        lib.tfidf_engine_new.argtypes = [
            ctypes.c_int, ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64]
        lib.tfidf_engine_free.argtypes = [ctypes.c_void_p]
        lib.tfidf_vocab_size.restype = ctypes.c_int64
        lib.tfidf_vocab_size.argtypes = [ctypes.c_void_p]
        lib.tfidf_vocab_lookup.restype = ctypes.c_int32
        lib.tfidf_vocab_lookup.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int]
        lib.tfidf_vocab_term.restype = ctypes.c_int64
        lib.tfidf_vocab_term.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_char_p,
            ctypes.c_int64]
        lib.tfidf_vocab_dump_size.restype = ctypes.c_int64
        lib.tfidf_vocab_dump_size.argtypes = [ctypes.c_void_p]
        lib.tfidf_vocab_dump.restype = ctypes.c_int64
        lib.tfidf_vocab_dump.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
        lib.tfidf_analyze_doc.restype = ctypes.c_int64
        lib.tfidf_analyze_doc.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64, ctypes.POINTER(ctypes.c_double)]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


NONASCII = -2
OVERFLOW = -1


class NativeEngine:
    """One native analyzer+vocabulary instance.

    All native calls hold ``self._mu``: ctypes releases the GIL, and the
    C++ side mutates shared unordered_maps (vocab + scratch) — concurrent
    HTTP upload handlers and searches would otherwise race. The pure-
    Python chain this replaces was GIL-serialized; the lock restores that
    guarantee.
    """

    def __init__(self, lowercase: bool = True,
                 stopwords: tuple[str, ...] = (),
                 max_token_length: int = 255) -> None:
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._mu = threading.Lock()
        stops = "\n".join(stopwords).encode("utf-8")
        self._h = ctypes.c_void_p(lib.tfidf_engine_new(
            int(lowercase), max_token_length, stops, len(stops)))
        # reusable output buffers, grown on demand (guarded by _mu)
        self._cap = 4096
        self._ids = np.empty(self._cap, np.int32)
        self._tfs = np.empty(self._cap, np.float32)
        self._len = ctypes.c_double(0.0)

    def __del__(self) -> None:
        h = getattr(self, "_h", None)
        if h:
            self._lib.tfidf_engine_free(h)
            self._h = None

    def vocab_size(self) -> int:
        with self._mu:
            return int(self._lib.tfidf_vocab_size(self._h))

    def lookup(self, term: str, add: bool) -> int | None:
        b = term.encode("utf-8")
        with self._mu:
            tid = self._lib.tfidf_vocab_lookup(self._h, b, len(b),
                                               int(add))
        return None if tid < 0 else int(tid)

    def term(self, tid: int) -> str:
        cap = 1024
        while True:
            buf = ctypes.create_string_buffer(cap)
            with self._mu:
                n = self._lib.tfidf_vocab_term(self._h, tid, buf, cap)
            if n == OVERFLOW:
                cap *= 4
                continue
            if n < 0:
                raise IndexError(f"term id {tid}")
            return buf.raw[:n].decode("utf-8")

    def dump_terms(self) -> list[str]:
        with self._mu:
            n = self._lib.tfidf_vocab_dump_size(self._h)
            if n == 0:
                return []
            buf = ctypes.create_string_buffer(int(n))
            wrote = self._lib.tfidf_vocab_dump(self._h, buf, n)
        assert wrote == n, (wrote, n)
        return buf.raw.decode("utf-8").split("\n")[:-1]

    def analyze(self, text: str, *, add: bool
                ) -> tuple[np.ndarray, np.ndarray, float] | None:
        """ASCII fast path: text -> (sorted ids, tfs, doc length).
        Returns None when the text needs the Python (Unicode) analyzer."""
        try:
            raw = text.encode("ascii")
        except UnicodeEncodeError:
            return None
        with self._mu:
            while True:
                n = self._lib.tfidf_analyze_doc(
                    self._h, raw, len(raw), int(add),
                    self._ids.ctypes.data_as(
                        ctypes.POINTER(ctypes.c_int32)),
                    self._tfs.ctypes.data_as(
                        ctypes.POINTER(ctypes.c_float)),
                    self._cap, ctypes.byref(self._len))
                if n == OVERFLOW:
                    self._cap *= 4
                    self._ids = np.empty(self._cap, np.int32)
                    self._tfs = np.empty(self._cap, np.float32)
                    continue
                if n == NONASCII:   # unreachable after the encode check
                    return None
                n = int(n)
                return (self._ids[:n].copy(), self._tfs[:n].copy(),
                        float(self._len.value))

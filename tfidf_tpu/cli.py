"""Command-line interface — the single-binary deployment surface.

The reference ships one Spring Boot fat jar that every node runs
(``app/ZookeeperLeaderElectionApplication.java``; k8s Deployment in
``README.MD:49-108``). The equivalent here is ``python -m tfidf_tpu``:

    serve        run a cluster node (worker + leader-candidate), optionally
                 with an embedded coordination service
    router       run a stateless query-plane router (scale-out reads;
                 mutations forward to the elected leader)
    coordinator  run only the coordination service (the "zookeeper" pod)
    ingest       build a local index from files/directories
    search       query a local index
    upload       client: send a document to a running cluster's leader
    query        client: search a running cluster
    status       client: node role + live membership + degraded summary
    drain        client: migrate a worker empty before decommission
    trace        client: fetch + render a distributed request trace
    autopilot    client: SLO-autopilot state, decision audit, kill switch
    bench        run the TPU benchmark
    faults       chaos tooling: list registered fault points

Config resolution (lowest to highest): dataclass defaults, --config JSON
file, TFIDF_* environment variables, explicit flags — mirroring the
reference's application.properties + env override scheme (SURVEY.md §5.6).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import urllib.parse

from tfidf_tpu.utils.config import Config, load_config
from tfidf_tpu.utils.logging import get_logger

log = get_logger("cli")


def _load_cfg(args, **overrides) -> Config:
    for name in ("host", "port", "documents_path", "index_path",
                 "coordinator_address", "model", "result_order",
                 "engine_mode"):
        v = getattr(args, name.replace("-", "_"), None)
        if v is not None:
            overrides[name] = v
    return load_config(getattr(args, "config", None), **overrides)


def cmd_serve(args) -> int:
    from tfidf_tpu.cluster.coordination import (CoordinationClient,
                                                CoordinationServer)
    from tfidf_tpu.cluster.node import SearchNode

    cfg = _load_cfg(args)
    if args.distributed:
        cfg = cfg.replace(distributed=True)
    if cfg.distributed:
        # multi-host mesh over DCN: must happen before any backend use so
        # jax.devices() spans the pod (auto-detected on TPU pods)
        from tfidf_tpu.parallel.mesh import initialize_multihost
        initialize_multihost(
            coordinator_address=cfg.dist_coordinator or None,
            num_processes=cfg.dist_num_processes or None,
            process_id=(cfg.dist_process_id
                        if cfg.dist_process_id >= 0 else None))
    server = None
    if args.embedded_coordinator:
        if cfg.coord_peers and not cfg.coord_data_dir:
            print("TFIDF_COORD_PEERS requires TFIDF_COORD_DATA_DIR "
                  "(quorum state must be durable)", file=sys.stderr)
            return 2
        try:
            peers = parse_peers(cfg.coord_peers)
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 2
        if peers:
            # ensemble member: bind THIS member's port from the peer
            # map (the connect string lists every member — its first
            # entry is usually someone else's address)
            if cfg.coord_node_id not in peers:
                print(f"TFIDF_COORD_NODE_ID {cfg.coord_node_id!r} "
                      "missing from TFIDF_COORD_PEERS map",
                      file=sys.stderr)
                return 2
            host = "0.0.0.0"
            port = peers[cfg.coord_node_id].rsplit(":", 1)[1]
        else:
            host, _, port = (
                cfg.coordinator_address.split(",")[0].strip()
                .partition(":"))
        server = CoordinationServer(
            host=host or "127.0.0.1", port=int(port or 0),
            session_timeout_s=cfg.session_timeout_s,
            data_dir=cfg.coord_data_dir or None,
            node_id=cfg.coord_node_id,
            peers=peers,
            election_timeout_s=cfg.ensemble_election_timeout_s,
            heartbeat_interval_s=cfg.ensemble_heartbeat_s,
            commit_timeout_s=cfg.ensemble_commit_timeout_s,
            snapshot_every=cfg.wal_snapshot_every,
            wal_fsync=cfg.wal_fsync).start()
        if not peers:
            # standalone: the node talks to its own embedded service;
            # ensemble members keep the full multi-member connect string
            cfg = cfg.replace(coordinator_address=server.address)
        log.info("embedded coordination service", address=server.address,
                 durable=bool(cfg.coord_data_dir))

    def factory():
        return CoordinationClient(
            cfg.coordinator_address,
            heartbeat_interval_s=cfg.heartbeat_interval_s)

    # restore-at-boot: a serving node with a checkpoint loads it and then
    # re-walks only documents written after the save (the reference
    # restores by re-walking everything, Worker.java:77-94)
    engine = None
    newer_than = None
    ckpt_dir = cfg.checkpoint_path or os.path.join(cfg.index_path,
                                                   "checkpoint")
    # fallback-aware restore: the manifest of every candidate version
    # is verified, corrupt ones are quarantined, and the newest INTACT
    # version wins — a torn or bit-rotted checkpoint costs a fallback
    # (or, at worst, the full re-walk below), never silently wrong
    # scores. Gated on checkpoint_versions, NOT isdir: a quarantine
    # leaves the published symlink dangling (isdir follows it to
    # False), and the intact .v<N-1> fallback must still be consulted.
    from tfidf_tpu.engine.checkpoint import (checkpoint_versions,
                                             restore_checkpoint)
    if checkpoint_versions(ckpt_dir):
        try:
            engine, meta = restore_checkpoint(ckpt_dir, cfg)
            created = meta.get("created_at")
            if created:
                newer_than = float(created) - 60.0   # clock-skew slack
            # reconcile deletions: the partial re-walk only UPSERTS, so
            # a document removed from the documents dir since the save
            # would otherwise be resurrected from the checkpoint forever
            # (the directory is the source of truth, Worker.java:77-94)
            if os.path.isdir(cfg.documents_path):
                removed = 0
                for e in list(engine.index.live_entries()):
                    if not os.path.isfile(
                            os.path.join(cfg.documents_path, e.name)):
                        engine.delete(e.name)
                        removed += 1
                if removed:
                    engine.commit()
                    log.info("dropped checkpointed docs missing from "
                             "documents dir", removed=removed)
            log.info("restored from checkpoint", dir=ckpt_dir,
                     docs=engine.index.num_live_docs)
        except Exception as e:
            log.warning("checkpoint restore failed; full rebuild",
                        err=repr(e))
            engine = None

    node = SearchNode(cfg, coord_factory=factory, engine=engine).start(
        rebuild_newer_than=newer_than)
    print(f"node up at {node.url} "
          f"({'leader' if node.is_leader() else 'worker'}); "
          f"coordinator {cfg.coordinator_address}", flush=True)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()   # the main thread parks, like Application.runApplication
    node.stop()
    if server is not None:
        server.close()
    return 0


def cmd_router(args) -> int:
    """Run one stateless query-plane router (cluster/router.py): no
    engine, no shard, no election — a scatter read plane behind
    ``/leader/start`` + ``/leader/download`` that follows the durable
    placement znode and forwards every mutation to the elected leader.
    Kill it and nothing is lost; run N and the interactive front door
    scales ~N-fold (README "Scale-out query plane")."""
    from tfidf_tpu.cluster.coordination import CoordinationClient
    from tfidf_tpu.cluster.router import QueryRouter

    cfg = _load_cfg(args)
    if args.coordinator:
        cfg = cfg.replace(coordinator_address=args.coordinator)

    def factory():
        return CoordinationClient(
            cfg.coordinator_address,
            heartbeat_interval_s=cfg.heartbeat_interval_s)

    router = QueryRouter(cfg, coord_factory=factory).start()
    print(f"router up at {router.url}; "
          f"coordinator {cfg.coordinator_address}", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    router.stop()
    return 0


def parse_peers(spec: str) -> dict[str, str]:
    """``"c0=host0:2181,c1=host1:2181"`` -> ``{"c0": "host0:2181", ...}``
    (the full ensemble member map, including this member)."""
    peers: dict[str, str] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        nid, sep, addr = part.partition("=")
        addr = addr.strip()
        host, psep, port = addr.rpartition(":")
        if (not sep or not nid.strip() or not host
                or not psep or not port.isdigit()):
            raise ValueError(f"bad peer spec {part!r} "
                             "(expected id=host:port)")
        peers[nid.strip()] = addr
    return peers


def cmd_coordinator(args) -> int:
    from tfidf_tpu.cluster.coordination import CoordinationServer

    cfg = _load_cfg(args)
    data_dir = args.data_dir or cfg.coord_data_dir or None
    node_id = args.node_id or cfg.coord_node_id
    try:
        peers = parse_peers(args.peers or cfg.coord_peers)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    if peers and not data_dir:
        print("--peers requires --data-dir (quorum state must be durable)",
              file=sys.stderr)
        return 2
    if peers and node_id not in peers:
        print(f"--node-id {node_id!r} missing from --peers map",
              file=sys.stderr)
        return 2
    listen = args.listen
    if not listen and node_id in peers:
        # default to this member's advertised port from the peer map
        listen = "0.0.0.0:" + peers[node_id].rsplit(":", 1)[1]
    host, _, port = (listen or "0.0.0.0:2181").partition(":")
    server = CoordinationServer(
        host=host, port=int(port or 2181),
        session_timeout_s=cfg.session_timeout_s,
        data_dir=data_dir, node_id=node_id, peers=peers,
        election_timeout_s=cfg.ensemble_election_timeout_s,
        heartbeat_interval_s=cfg.ensemble_heartbeat_s,
        commit_timeout_s=cfg.ensemble_commit_timeout_s,
        snapshot_every=cfg.wal_snapshot_every,
        wal_fsync=cfg.wal_fsync).start()
    mode = ("ensemble member" if peers
            else "durable" if data_dir else "in-memory")
    print(f"coordination service at {server.address} ({mode})", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    server.close()
    return 0


def cmd_ingest(args) -> int:
    from tfidf_tpu.engine.checkpoint import save_checkpoint
    from tfidf_tpu.engine.engine import Engine

    from tfidf_tpu.ops.analyzer import UnsupportedMediaType

    cfg = _load_cfg(args)
    engine = Engine(cfg)
    n = 0

    def ingest_one(name: str, data: bytes, save: bool) -> int:
        try:
            engine.ingest_bytes(name, data, save_to_disk=save)
            return 1
        except UnsupportedMediaType as e:
            # one stray binary must not abort a directory ingest
            print(f"skipping {name}: {e}", file=sys.stderr)
            return 0

    for path in args.paths:
        if os.path.isdir(path):
            # ingest files only; one commit at the end covers everything
            for dirpath, _dirnames, filenames in sorted(os.walk(path)):
                for fn in sorted(filenames):
                    full = os.path.join(dirpath, fn)
                    rel = os.path.relpath(full, path)
                    with open(full, "rb") as f:
                        n += ingest_one(rel, f.read(), False)
        else:
            with open(path, "rb") as f:
                n += ingest_one(os.path.basename(path), f.read(), True)
    engine.commit()
    if args.checkpoint:
        save_checkpoint(engine, args.checkpoint)
    print(json.dumps({"docs": n, "vocab": len(engine.vocab),
                      "nnz": engine.index.snapshot.nnz}))
    return 0


def cmd_search(args) -> int:
    from tfidf_tpu.engine.checkpoint import load_checkpoint
    from tfidf_tpu.engine.engine import Engine

    cfg = _load_cfg(args)
    if args.checkpoint:
        engine = load_checkpoint(args.checkpoint, cfg)
    else:
        engine = Engine(cfg)
        engine.build_from_directory()
    for q in args.queries:
        hits = engine.search(q, k=args.k)
        print(json.dumps({"query": q,
                          "hits": [{"name": h.name, "score": h.score}
                                   for h in hits]}))
    return 0


def _leader_url(args) -> str:
    return args.leader.rstrip("/")


def _shed_aware_post(url: str, data: bytes,
                     content_type: str = "application/json",
                     who: str = "leader",
                     return_headers: bool = False):
    """POST to a front door honoring its admission layer: a 429 shed
    is retried only AFTER its ``Retry-After`` hint has elapsed (the
    default classifier + RetryPolicy floor — see resilience.py), and a
    request still shed after the bounded attempts exits with the shed
    message instead of a traceback. The CLI must model the polite
    client: hammering a saturated front door from the operator's own
    tooling would amplify the overload the shed is relieving.

    One protocol for both the ``--leader`` and ``--via-router`` paths
    (``who`` names the shedding side in the message);
    ``return_headers=True`` returns ``(reply headers, body)`` — the
    router path prints the route stamp / degraded markers from them."""
    import urllib.error
    import urllib.request

    from tfidf_tpu.cluster.resilience import RetryPolicy, retry_after_of

    def once():
        req = urllib.request.Request(
            url, data=data, headers={"Content-Type": content_type})
        with urllib.request.urlopen(req, timeout=60.0) as r:
            return dict(r.headers), r.read()

    policy = RetryPolicy(max_attempts=3, base_delay_s=0.05, name="cli")
    try:
        hdrs, body = policy.call(once)
    except urllib.error.HTTPError as e:
        ra = retry_after_of(e)
        if ra is None:
            raise
        print(f"{who} is shedding load (429, reason="
              f"{e.headers.get('X-Shed-Reason', '?')}): retry after "
              f"{ra:.3f}s", file=sys.stderr)
        raise SystemExit(75)   # EX_TEMPFAIL: try again later
    return (hdrs, body) if return_headers else body


def cmd_upload(args) -> int:

    if getattr(args, "batch", False):
        from tfidf_tpu.ops.analyzer import (UnsupportedMediaType,
                                            extract_text)

        # bulk path: expand dirs (relative paths as names, same keying
        # as cmd_ingest — basenames would silently upsert same-named
        # files from different subdirectories over each other), extract
        # text CLIENT-side (the Tika contract: binaries are refused
        # here, not lossily decoded past the worker's 415 gate), ship
        # one /leader/upload-batch request per chunk of 500
        files: list[tuple[str, str]] = []     # (name, path)
        for path in args.files:
            if os.path.isdir(path):
                for dirpath, _d, fns in sorted(os.walk(path)):
                    files.extend(
                        (os.path.relpath(os.path.join(dirpath, fn),
                                         path), os.path.join(dirpath, fn))
                        for fn in sorted(fns))
            else:
                files.append((os.path.basename(path), path))
        total = 0
        failed = False
        for lo in range(0, len(files), 500):
            docs = []
            for name, p in files[lo:lo + 500]:
                with open(p, "rb") as f:
                    raw = f.read()
                try:
                    docs.append({"name": name,
                                 "text": extract_text(raw)})
                except UnsupportedMediaType as e:
                    print(f"skipped {name}: {e}", file=sys.stderr)
            if not docs:
                continue
            resp = json.loads(_shed_aware_post(
                _leader_url(args) + "/leader/upload-batch",
                json.dumps(docs).encode()))
            total += sum(resp.get("placed", {}).values())
            for s in resp.get("skipped", ()):
                print(f"skipped {s['name']}: {s['error']}",
                      file=sys.stderr)
            for w, err in resp.get("errors", {}).items():
                print(f"worker {w} failed: {err}", file=sys.stderr)
                failed = True
        print(f"{total} files uploaded and indexed")
        return 1 if failed else 0
    for path in args.files:
        with open(path, "rb") as f:
            data = f.read()
        name = urllib.parse.quote(os.path.basename(path))
        resp = _shed_aware_post(
            _leader_url(args) + f"/leader/upload?name={name}",
            data, content_type="application/octet-stream")
        print(resp.decode())
    return 0


def cmd_query(args) -> int:
    via = getattr(args, "via_router", None)
    if not via and not args.leader:
        print("query needs --leader URL or --via-router URL",
              file=sys.stderr)
        return 2
    payload = {"query": " ".join(args.query)}
    # hybrid plan (wire v3): mode/fusion are ADDITIVE fields — a plain
    # sparse query sends neither, staying byte-identical to a v2
    # request (README "Hybrid retrieval")
    mode = getattr(args, "mode", None)
    if mode and mode != "sparse":
        payload["mode"] = mode
        if getattr(args, "fusion", None):
            payload["fusion"] = args.fusion
    body = json.dumps(payload).encode()
    target = (via.rstrip("/") if via else _leader_url(args))
    # surface the read plane's honesty headers — which stages ran
    # (X-Search-Stages carries the fusion method + weights), which
    # placement world routed the request, and whether the results are
    # degraded/stale. Same polite-shed protocol on both paths: leaders
    # and routers each run an admission controller, so a 429 here is
    # expected.
    hdrs, out = _shed_aware_post(
        target + "/leader/start", body,
        who=("router" if via else "leader"), return_headers=True)
    for h in ("X-Search-Stages", "X-Route-Epoch", "X-Route-Generation",
              "X-Scatter-Degraded"):
        v = hdrs.get(h)
        if v:
            print(f"{h}: {v}", file=sys.stderr)
    print(out.decode())
    return 0


def cmd_status(args) -> int:
    from tfidf_tpu.cluster.node import http_get

    url = _leader_url(args)
    metrics = json.loads(http_get(url + "/api/metrics"))
    out = {"status": http_get(url + "/api/status").decode(),
           "services": json.loads(http_get(url + "/api/services")),
           "metrics": metrics}
    # failure-semantics summary (README "Failure semantics"): was the
    # last scatter-gather fan-out degraded, and which workers' circuit
    # breakers are not closed right now
    degraded = {
        "last_scatter_degraded": bool(metrics.get("scatter_degraded", 0)),
        "last_scatter_workers_attempted":
            int(metrics.get("scatter_last_attempted", 0)),
        "last_scatter_workers_responded":
            int(metrics.get("scatter_last_responded", 0)),
        "circuit_open_workers":
            sorted(w for w, s in metrics.get("breaker_states", {}).items()
                   if s != "closed"),
    }
    out["degraded"] = degraded
    # replication summary (README "Replication & failover semantics"):
    # how often failed owners' slices failed over to surviving replicas,
    # whether any document currently has no live scorer, and what the
    # anti-entropy repair has moved
    out["replication"] = {
        "last_scatter_failovers":
            int(metrics.get("scatter_last_failovers", 0)),
        "last_scatter_dark_docs":
            int(metrics.get("scatter_last_dark", 0)),
        "failover_reads_total": int(metrics.get("scatter_failovers", 0)),
        "hedge_wins_total": int(metrics.get("scatter_hedge_wins", 0)),
        "repair_docs_replicated":
            int(metrics.get("repair_docs_replicated", 0)),
        "repair_docs_trimmed": int(metrics.get("repair_docs_trimmed", 0)),
    }
    # elastic-rebalance summary (README "Elastic rebalancing & drain"):
    # in-flight migrations/drains and the lifetime moved/failed totals
    out["rebalance"] = {
        "active_migrations": int(metrics.get("rebalance_active", 0)),
        "draining_workers":
            int(metrics.get("rebalance_draining_workers", 0)),
        "moved_docs_total": int(metrics.get("rebalance_moved_docs", 0)),
        "failures_total": int(metrics.get("rebalance_failures", 0)),
        "drains_started": int(metrics.get("rebalance_drains_started", 0)),
        "drains_completed":
            int(metrics.get("rebalance_drains_completed", 0)),
    }
    # overload summary (README "Overload & admission control"): is the
    # front door shedding, why, and is the result cache earning its keep
    hits = metrics.get("cache_hits", 0)
    misses = metrics.get("cache_misses", 0)
    # SLO-autopilot summary (README "SLO autopilot"): is the closed
    # loop steering, where each managed knob sits vs its static config
    # value, and how fresh the last decision is. Best-effort: a
    # pre-autopilot node simply has no block.
    try:
        ap = json.loads(http_get(url + "/api/autopilot?recent=0"))
        snap = ap.get("autopilot", {})
        out["autopilot"] = {
            "enabled": bool(snap.get("enabled")),
            "knobs": {
                k: {"current": v.get("current"),
                    "static": v.get("static"),
                    "adjustments": v.get("adjustments", 0)}
                for k, v in snap.get("knobs", {}).items()},
            "decisions_recorded": snap.get("decisions_recorded", 0),
            "last_decision_age_s": snap.get("last_decision_age_s"),
        }
    except Exception:
        pass
    # scale-out query plane summary (README "Scale-out query plane"):
    # the registered stateless routers, each one's placement
    # (epoch, generation) lag behind the leader's authoritative map,
    # staleness, and per-router cache hit rate. Best-effort: a
    # pre-router node simply has no block; an unreachable router is
    # listed as such rather than hiding the tier.
    try:
        router_urls = json.loads(http_get(url + "/api/routers"))
    except Exception:
        router_urls = []
    if router_urls:
        ref = {}
        try:
            leader_addr = (json.loads(http_get(url + "/api/leader"))
                           .get("leader")) or url
            ref = json.loads(http_get(
                str(leader_addr).rstrip("/") + "/api/router",
                timeout=3.0)).get("placement", {})
        except Exception:
            pass
        entries = []
        for r in router_urls:
            try:
                snap = json.loads(http_get(
                    str(r).rstrip("/") + "/api/router", timeout=3.0))
            except Exception:
                entries.append({"url": r, "reachable": False})
                continue
            pl = snap.get("placement", {})
            entry = {
                "url": r, "reachable": True,
                "placement_epoch": pl.get("epoch"),
                "placement_gen": pl.get("gen"),
                "view_age_s": pl.get("age_s"),
                "stale": bool(pl.get("stale")),
                "cache_hit_rate":
                    snap.get("cache", {}).get("hit_rate", 0.0),
                "writes_proxied": snap.get("writes_proxied", 0),
            }
            # lag vs the leader's authoritative map, in generations
            # and leadership epochs (None when either side is unknown)
            if (ref.get("gen") is not None
                    and pl.get("gen") is not None):
                entry["gen_lag"] = max(
                    0, int(ref["gen"]) - int(pl["gen"]))
            if (ref.get("epoch") is not None
                    and pl.get("epoch") is not None):
                entry["epoch_lag"] = max(
                    0, int(ref["epoch"]) - int(pl["epoch"]))
            entries.append(entry)
        out["routers"] = {"count": len(router_urls),
                          "routers": entries}
    # fleet wire-version summary (README "Versioning & upgrades"):
    # each member's declared proto version from /api/health. A member
    # whose health reply predates versioning speaks the implicit
    # version 1. A mixed-version fleet is normal MID-upgrade and a
    # finding at any other time — `status` flags it instead of hiding
    # it behind per-node queries.
    members = [("node", url)] + [("node", str(s))
                                 for s in out["services"]] \
        + [("router", str(r)) for r in router_urls]
    versions = []
    # embedding-column summary (README "Hybrid retrieval"): per-member
    # dense-plane footprint from the same /api/health sweep — model,
    # dims, docs embedded, bytes resident. A member with the dense
    # plane off (or predating it) simply has no row.
    columns = []
    # tiered-postings summary (README "Tiered storage & block-max
    # skipping"): per-member hot/cold segment counts, HBM bytes vs
    # budget, hit/skip rates from the same sweep. A member with
    # tiering off (or predating it) simply has no row.
    tiers = []
    # compute-plane health summary (README "Compute-plane failure
    # semantics"): per-member device state machine from the same
    # sweep. A member predating it simply has no row.
    compute = []
    for role, member in members:
        try:
            h = json.loads(http_get(
                member.rstrip("/") + "/api/health", timeout=3.0))
            versions.append({"url": member,
                             "role": h.get("role", role),
                             "proto_version":
                                 int(h.get("proto_version", 1)),
                             "reachable": True})
            emb = h.get("embedding")
            if emb:
                columns.append({"url": member,
                                "model": emb.get("model"),
                                "dim": emb.get("dim"),
                                "docs_embedded": int(emb.get("docs", 0)),
                                "bytes_resident":
                                    int(emb.get("bytes", 0))})
            tier = h.get("tier")
            if tier and tier.get("enabled"):
                tiers.append({
                    "url": member,
                    "hot_segments": int(tier.get("hot_segments", 0)),
                    "cold_segments": int(tier.get("cold_segments", 0)),
                    "hot_bytes": int(tier.get("hot_bytes", 0)),
                    "budget_bytes": int(tier.get("budget_bytes", 0)),
                    "hit_rate": tier.get("hit_rate", 0.0),
                    "skip_rate": tier.get("skip_rate", 0.0),
                    "ring_stall_s": tier.get("ring_stall_s", 0.0)})
            comp = h.get("compute")
            if comp:
                compute.append({
                    "url": member,
                    "state": comp.get("state"),
                    "consecutive_faults":
                        int(comp.get("consecutive_faults", 0)),
                    "total_faults": int(comp.get("total_faults", 0)),
                    "faults_by_kind": comp.get("faults_by_kind", {}),
                    "recovery_probes":
                        int(comp.get("recovery_probes", 0)),
                    "fallback_available":
                        bool(comp.get("fallback_available"))})
        except Exception:
            versions.append({"url": member, "role": role,
                             "proto_version": None,
                             "reachable": False})
    seen = sorted({v["proto_version"] for v in versions
                   if v["proto_version"] is not None})
    out["versions"] = {
        "members": versions,
        "proto_versions_seen": seen,
        "mixed_versions": len(seen) > 1,
    }
    out["embedding"] = {
        "enabled": bool(columns),
        "columns": columns,
        "docs_embedded_total":
            sum(c["docs_embedded"] for c in columns),
        "bytes_resident_total":
            sum(c["bytes_resident"] for c in columns),
    }
    out["tier"] = {
        "enabled": bool(tiers),
        "nodes": tiers,
        "hot_segments_total": sum(t["hot_segments"] for t in tiers),
        "cold_segments_total": sum(t["cold_segments"] for t in tiers),
        "hot_bytes_total": sum(t["hot_bytes"] for t in tiers),
    }
    out["compute"] = {
        "nodes": compute,
        "sick_nodes": sorted(c["url"] for c in compute
                             if c["state"] == "sick"),
        "degraded_nodes": sorted(c["url"] for c in compute
                                 if c["state"] == "degraded"),
        "fallback_served_total":
            int(metrics.get("compute_fallback_served", 0)),
        "poison_quarantined_total":
            int(metrics.get("poison_quarantined", 0)),
    }
    out["admission"] = {
        "admitted_total": int(metrics.get("admission_admitted", 0)),
        "shed_total": int(metrics.get("admission_shed_total", 0)),
        "shed_rate_limited":
            int(metrics.get("admission_shed_rate_limited", 0)),
        "shed_backpressure":
            int(metrics.get("admission_shed_backpressure", 0)),
        "last_queue_depth": metrics.get("admission_last_depth", 0),
        "cache_hits": int(hits),
        "cache_misses": int(misses),
        "cache_hit_rate": round(hits / (hits + misses), 3)
            if (hits + misses) else 0.0,
        "cache_entries": int(metrics.get("cache_entries", 0)),
    }
    print(json.dumps(out, indent=2))
    return 0


def cmd_drain(args) -> int:
    """Planned decommission: ask the leader to migrate a worker empty
    (live, crash-safe) so it can leave the cluster with zero loss."""
    import time as _time

    from tfidf_tpu.cluster.node import http_get, http_post

    url = _leader_url(args)
    body = json.dumps({"worker": args.worker,
                       "cancel": bool(args.cancel)}).encode()
    resp = json.loads(http_post(url + "/api/drain", body))
    print(json.dumps(resp, indent=2))
    if args.cancel or not args.wait:
        return 0
    # poll until the worker holds nothing and its deletes landed; a
    # transient poll failure (leader restart, leadership change mid-
    # drain answering 409) is retried until the deadline — the wait
    # loop exists precisely to ride out such windows
    deadline = _time.monotonic() + args.wait_timeout
    last_err = None
    while _time.monotonic() < deadline:
        try:
            q = urllib.parse.quote(args.worker)
            st = json.loads(http_get(url + f"/api/drain?worker={q}"))
            if st.get("drained"):
                print(json.dumps(st, indent=2))
                return 0
        except Exception as e:
            last_err = e
        _time.sleep(1.0)
    print("drain did not complete in time"
          + (f" (last poll error: {last_err!r})" if last_err else ""),
          file=sys.stderr)
    return 1


def cmd_trace(args) -> int:
    """Fetch and render a distributed trace (``GET /api/trace``): by
    trace id (the ``X-Trace-Id`` reply header every /leader/* response
    carries, also stamped on slow-query log lines), or the most recent
    spans. Span rings are PER NODE — a real multi-process cluster keeps
    the leader-side spans on the leader and the worker-side
    continuations on each worker — so a by-id fetch fans out to every
    node in ``/api/services`` and merges (deduping by span id; a
    one-process test cluster shares one ring). ``--chrome FILE`` writes
    Chrome-trace/Perfetto JSON instead of the text timeline."""
    from tfidf_tpu.cluster.node import http_get
    from tfidf_tpu.utils.tracing import render_trace_tree, to_chrome_trace

    url = _leader_url(args)
    if args.trace_id:
        nodes = {url}
        try:
            nodes.update(str(u).rstrip("/") for u in json.loads(
                http_get(url + "/api/services")))
        except Exception as e:
            print(f"warning: could not list cluster nodes ({e!r}); "
                  "rendering this node's spans only", file=sys.stderr)
        try:
            # /api/services lists only WORKERS (the leader leaves the
            # pool on promotion) — but the leader's ring holds the
            # request/scatter/slice spans, so it must be queried even
            # when --leader actually points at a worker
            addr = json.loads(http_get(
                url + "/api/leader")).get("leader")
            if addr:
                nodes.add(str(addr).rstrip("/"))
        except Exception:
            pass   # pre-/api/leader node: the entry URL still counts
        unreachable: set[str] = set()

        def fetch(nu: str, tid: str) -> list[dict]:
            try:
                # short per-node budget: the tool's whole point is
                # tracing through failures, so a partitioned worker
                # must cost seconds, not the default urlopen timeout
                got = json.loads(http_get(
                    nu + "/api/trace/" + urllib.parse.quote(tid),
                    timeout=3.0))
            except Exception:
                unreachable.add(nu)   # a dead worker's spans died
                return []             # with it — render the rest
            return got.get("spans", [])

        # two waves: the request id first, then every trace id the
        # REQUEST's own spans link to (the coalescer boundary —
        # worker-side continuations live under the BATCH trace id, so
        # a worker's ring answers only the linked id, not the request
        # id). The final span set is FILTERED to those resolved ids:
        # batch spans link every request they absorbed, and the
        # servers' own one-hop expansion would otherwise pull
        # unrelated sibling requests into this timeline.
        from concurrent.futures import ThreadPoolExecutor
        ordered = sorted(nodes)
        with ThreadPoolExecutor(min(8, len(ordered))) as pool:
            wave1_by_node = dict(zip(ordered, pool.map(
                lambda nu: fetch(nu, args.trace_id), ordered)))
            wave1 = [s for lst in wave1_by_node.values() for s in lst]
            ids = {args.trace_id}
            for s in wave1:
                if s["trace_id"] == args.trace_id:
                    ids.update(t["trace_id"]
                               for t in s.get("links", []))
            collected = list(wave1)
            # this wave skips nodes that answered wave 1: their own
            # one-hop link expansion already covered the linked ids
            targets = [(nu, tid)
                       for tid in sorted(ids - {args.trace_id})
                       for nu in ordered
                       if not wave1_by_node.get(nu)
                       and nu not in unreachable]
            for got in pool.map(lambda t: fetch(*t), targets):
                collected.extend(got)
        if unreachable:
            print("warning: unreachable node(s) skipped: "
                  + ", ".join(sorted(unreachable)), file=sys.stderr)
        spans, seen = [], set()
        for s in collected:
            if s["trace_id"] in ids and s["span_id"] not in seen:
                seen.add(s["span_id"])
                spans.append(s)
        spans.sort(key=lambda s: s["start_s"])
    else:
        data = json.loads(http_get(
            url + f"/api/trace?recent={int(args.recent)}"))
        spans = data.get("spans", [])
    if args.chrome:
        with open(args.chrome, "w", encoding="utf-8") as f:
            json.dump(to_chrome_trace(spans), f)
        print(f"{len(spans)} span(s) -> {args.chrome} "
              "(load in chrome://tracing or ui.perfetto.dev)")
        return 0
    if not spans:
        print("(no spans"
              + (f" for trace {args.trace_id}" if args.trace_id else "")
              + " — is tracing sampled out, or the ring already "
                "recycled?)")
        return 1
    print(render_trace_tree(spans))
    return 0


def cmd_autopilot(args) -> int:
    """Inspect (and toggle) the SLO autopilot: ``GET /api/autopilot``
    rendered as a knob table plus the newest decision-audit records —
    which sensor inputs were read, what was decided, what was written.
    ``--enable`` / ``--disable`` flip the runtime kill switch
    (disabling reverts every managed knob to static config before the
    command returns). The loop runs on the LEADER, so the request is
    routed there via ``/api/leader`` when ``--leader`` actually points
    at a worker."""
    from tfidf_tpu.cluster.node import http_get, http_post

    url = _leader_url(args)
    try:
        addr = json.loads(http_get(url + "/api/leader")).get("leader")
        if addr:
            url = str(addr).rstrip("/")
    except Exception:
        pass   # pre-/api/leader node: talk to the given URL
    if args.enable or args.disable:
        body = json.dumps({"enabled": bool(args.enable)}).encode()
        resp = json.loads(http_post(url + "/api/autopilot", body))
        snap = resp["autopilot"]
    else:
        resp = json.loads(http_get(
            url + f"/api/autopilot?recent={int(args.recent)}"))
        snap = resp["autopilot"]
    if args.json:
        print(json.dumps(resp, indent=2))
        return 0
    state = "ENABLED" if snap.get("enabled") else "disabled"
    print(f"autopilot {state} (node {url})")
    print(f"  interval {snap.get('interval_ms')}ms, "
          f"hysteresis {snap.get('hysteresis')}, "
          f"step {snap.get('step')}, confirm {snap.get('confirm')}, "
          f"p99 SLO {snap.get('p99_slo_ms')}ms")
    knobs = snap.get("knobs", {})
    if knobs:
        w = max(len(k) for k in knobs)
        print(f"  {'knob'.ljust(w)}  current   static    "
              f"[floor..ceiling]  dir  adjusts  last")
        for k, v in knobs.items():
            age = v.get("last_adjust_age_s")
            print(f"  {k.ljust(w)}  {v['current']:>8}  "
                  f"{v['static']:>8}  [{v['floor']:g}.."
                  f"{v['ceiling']:g}]  {v['last_direction']:>+2d}  "
                  f"{v['adjustments']:>7}  "
                  f"{(str(age) + 's ago') if age is not None else '-'}")
    decs = resp.get("decisions", [])
    if decs:
        print(f"  last {len(decs)} decision(s):")
        for d in decs:
            tail = (f" {d['current']} -> {d['new']}"
                    if d.get("applied") else f" (target {d['target']})")
            inp = ", ".join(f"{k}={v}"
                            for k, v in (d.get("inputs") or {}).items())
            print(f"    #{d['seq']} {d['knob']}: {d['reason']}{tail}"
                  + (f"  [{inp}]" if inp else ""))
    return 0


def cmd_faults(args) -> int:
    """``faults list``: print every fault point compiled into the tree
    (name + firing site) so chaos configs can be checked against the
    code instead of silently going stale."""
    from tfidf_tpu.utils.faults import KNOWN_FAULT_POINTS

    if args.action == "list":
        try:
            for name in sorted(KNOWN_FAULT_POINTS):
                print(f"{name}\t{KNOWN_FAULT_POINTS[name]}")
        except BrokenPipeError:   # e.g. `faults list | head` — not an error
            import os
            os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return 0
    print(f"unknown faults action: {args.action}", file=sys.stderr)
    return 2


def cmd_quarantine(args) -> int:
    """``quarantine``: inspect (or ``--clear``) the poison-query
    quarantine on a node or router. The snapshot shows every tracked
    fingerprint with the distinct replicas that blamed it and how old
    the verdict is; ``--clear`` drops the table (operator override
    after a bad deploy is rolled back) and prints how many quarantined
    entries were released."""
    from tfidf_tpu.cluster.node import http_get, http_post

    url = args.url.rstrip("/")
    if args.clear:
        resp = json.loads(http_post(url + "/api/quarantine", b"{}"))
        print(json.dumps(resp, indent=1))
        return 0
    snap = json.loads(http_get(url + "/api/quarantine"))
    print(json.dumps(snap, indent=1))
    return 0


def cmd_scrub(args) -> int:
    """``scrub``: storage-integrity verification. With ``--url`` it
    triggers one scrub pass on a RUNNING node (``POST /admin/scrub`` —
    the same pass the leader's sweep loop runs every
    ``storage_scrub_ms``); otherwise it verifies the local on-disk
    state offline: every checkpoint version's manifest and every
    placed-docs CRC against the ledger. Exit 1 on any corruption —
    the loud-refusal half of the storage contract."""
    from tfidf_tpu.utils import storage as st

    if args.url:
        from tfidf_tpu.cluster.node import http_post
        resp = json.loads(http_post(
            args.url.rstrip("/") + "/admin/scrub", b"{}"))
        print(json.dumps(resp, indent=1))
        return 1 if resp.get("unrepaired") \
            or resp.get("checkpoints_quarantined") else 0
    cfg = _load_cfg(args)
    ckpt_bad = 0
    from tfidf_tpu.engine.checkpoint import checkpoint_versions
    ckpt = cfg.checkpoint_path or os.path.join(cfg.index_path,
                                               "checkpoint")
    for vdir in checkpoint_versions(ckpt):
        problems = st.verify_manifest(vdir)
        status = "OK" if not problems else "; ".join(problems)
        print(f"checkpoint {vdir}: {status}")
        ckpt_bad += bool(problems)
    ledger = st.CrcLedger(os.path.join(cfg.index_path,
                                       "placed_docs.crc.json"))
    store = os.path.join(cfg.index_path, "placed_docs")
    checked = store_bad = 0
    for name in sorted(ledger.names()):
        path = os.path.join(store, name)
        if not os.path.isfile(path):
            continue
        checked += 1
        if st.file_crc(path) != ledger.get(name):
            print(f"placed_docs {name}: CRC MISMATCH")
            store_bad += 1
    print(f"placed_docs: {checked} file(s) checked, "
          f"{store_bad} problem(s); checkpoints: {ckpt_bad} problem(s)")
    return 1 if ckpt_bad or store_bad else 0


def cmd_bench(args) -> int:
    # bench.py lives at the repo root, not inside the package
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if not os.path.exists(os.path.join(root, "bench.py")):
        print("bench.py not found (requires a repo checkout)",
              file=sys.stderr)
        return 1
    sys.path.insert(0, root)
    import bench

    bench.main()
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tfidf_tpu",
        description="TPU-native distributed full-text search framework")
    p.add_argument("--config", help="JSON config file")
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("serve", help="run a cluster node")
    s.add_argument("--host")
    s.add_argument("--port", type=int)
    s.add_argument("--documents-path")
    s.add_argument("--index-path")
    s.add_argument("--coordinator-address")
    s.add_argument("--model", choices=["bm25", "tfidf", "tfidf_cosine"])
    s.add_argument("--result-order", choices=["score", "name"])
    s.add_argument("--engine-mode", choices=["local", "mesh"],
                   help="mesh: serve from ShardedArrays on the device "
                        "mesh (distributed shard_map search)")
    s.add_argument("--embedded-coordinator", action="store_true",
                   help="also run the coordination service in-process")
    s.add_argument("--distributed", action="store_true",
                   help="multi-host: jax.distributed.initialize before "
                        "building the mesh (auto-detected on TPU pods; "
                        "see TFIDF_DIST_* / JAX_* env vars)")
    s.set_defaults(fn=cmd_serve)

    s = sub.add_parser("coordinator", help="run the coordination service")
    s.add_argument("--listen", help="host:port (default 0.0.0.0:2181, or "
                                    "this member's port from --peers)")
    s.add_argument("--data-dir",
                   help="durable state dir (WAL + snapshots); a restarted "
                        "coordinator recovers its full znode tree and "
                        "sessions from it")
    s.add_argument("--node-id", help="this ensemble member's id")
    s.add_argument("--peers",
                   help="full ensemble member map incl. self: "
                        "id0=host0:2181,id1=host1:2181,id2=host2:2181 "
                        "(majority quorum commits every write)")
    s.set_defaults(fn=cmd_coordinator)

    s = sub.add_parser("router",
                       help="run a stateless query-plane router")
    s.add_argument("--coordinator",
                   help="coordination connect string "
                        "(host:port[,host:port...]); defaults to "
                        "TFIDF_COORDINATOR_ADDRESS")
    s.add_argument("--host")
    s.add_argument("--port", type=int)
    s.set_defaults(fn=cmd_router)

    s = sub.add_parser("ingest", help="index files/dirs locally")
    s.add_argument("paths", nargs="+")
    s.add_argument("--documents-path")
    s.add_argument("--checkpoint", help="save a checkpoint here")
    s.add_argument("--model", choices=["bm25", "tfidf", "tfidf_cosine"])
    s.add_argument("--engine-mode", choices=["local", "mesh"])
    s.set_defaults(fn=cmd_ingest)

    s = sub.add_parser("search", help="query a local index")
    s.add_argument("queries", nargs="+")
    s.add_argument("-k", type=int, default=10)
    s.add_argument("--documents-path")
    s.add_argument("--checkpoint", help="load this checkpoint")
    s.add_argument("--model", choices=["bm25", "tfidf", "tfidf_cosine"])
    s.add_argument("--engine-mode", choices=["local", "mesh"])
    s.set_defaults(fn=cmd_search)

    s = sub.add_parser("upload", help="upload documents to a cluster")
    s.add_argument("files", nargs="+")
    s.add_argument("--leader", required=True, help="leader base URL")
    s.add_argument("--batch", action="store_true",
                   help="bulk-ingest text files (dirs expand; one "
                        "upload-batch request per 500 docs)")
    s.set_defaults(fn=cmd_upload)

    s = sub.add_parser("query", help="search a running cluster")
    s.add_argument("query", nargs="+")
    s.add_argument("--leader", help="leader base URL")
    s.add_argument("--via-router", metavar="URL",
                   help="route the read through a stateless router "
                        "(prints the X-Route-Epoch/Generation stamp "
                        "and any degraded marker to stderr)")
    s.add_argument("--mode", choices=["sparse", "dense", "hybrid"],
                   default="sparse",
                   help="retrieval plan: sparse TF-IDF (default), "
                        "dense embedding cosine, or hybrid fused "
                        "top-k (prints the stages ran + fusion "
                        "weights to stderr via X-Search-Stages)")
    s.add_argument("--fusion", choices=["rrf", "wsum"],
                   help="hybrid fusion method (default: the cluster's "
                        "fusion_method config)")
    s.set_defaults(fn=cmd_query)

    s = sub.add_parser("status", help="node role + membership + metrics")
    s.add_argument("--leader", required=True, help="any node's base URL")
    s.set_defaults(fn=cmd_status)

    s = sub.add_parser("drain",
                       help="migrate a worker empty before decommission")
    s.add_argument("worker", help="worker base URL to drain")
    s.add_argument("--leader", required=True, help="leader base URL")
    s.add_argument("--cancel", action="store_true",
                   help="cancel an in-progress drain")
    s.add_argument("--wait", action="store_true",
                   help="poll until the worker is fully drained")
    s.add_argument("--wait-timeout", type=float, default=300.0)
    s.set_defaults(fn=cmd_drain)

    s = sub.add_parser("trace",
                       help="fetch + render a distributed trace")
    s.add_argument("trace_id", nargs="?", default="",
                   help="trace id (X-Trace-Id reply header); omit for "
                        "the most recent spans")
    s.add_argument("--leader", required=True, help="any node's base URL")
    s.add_argument("--recent", type=int, default=100,
                   help="span count when no trace id is given")
    s.add_argument("--chrome", metavar="FILE",
                   help="write Chrome-trace/Perfetto JSON here instead "
                        "of the text timeline")
    s.set_defaults(fn=cmd_trace)

    s = sub.add_parser("autopilot",
                       help="inspect / toggle the SLO autopilot")
    s.add_argument("--leader", required=True, help="any node's base URL "
                                                   "(routed to the leader)")
    s.add_argument("--recent", type=int, default=10,
                   help="decision-audit records to show")
    toggle = s.add_mutually_exclusive_group()
    toggle.add_argument("--enable", action="store_true",
                        help="turn the control loop on")
    toggle.add_argument("--disable", action="store_true",
                        help="kill switch: off + revert every knob to "
                             "static config")
    s.add_argument("--json", action="store_true",
                   help="raw JSON instead of the rendered table")
    s.set_defaults(fn=cmd_autopilot)

    s = sub.add_parser("bench", help="run the TPU benchmark")
    s.set_defaults(fn=cmd_bench)

    s = sub.add_parser("scrub",
                       help="storage-integrity verification: checkpoint "
                            "manifests + placed-docs CRC ledger")
    s.add_argument("--url",
                   help="trigger one scrub pass on a running node "
                        "(POST /admin/scrub) instead of offline "
                        "verification")
    s.add_argument("--index-path")
    s.add_argument("--documents-path")
    s.set_defaults(fn=cmd_scrub)

    s = sub.add_parser("quarantine",
                       help="inspect / clear the poison-query "
                            "quarantine on a node or router")
    s.add_argument("url", help="node or router base URL")
    s.add_argument("--clear", action="store_true",
                   help="drop the quarantine table (operator override)")
    s.set_defaults(fn=cmd_quarantine)

    s = sub.add_parser("faults",
                       help="chaos tooling: inspect fault points")
    s.add_argument("action", choices=["list"],
                   help="list: print all registered fault points")
    s.set_defaults(fn=cmd_faults)
    return p


def _apply_platform_override() -> None:
    """``TFIDF_JAX_PLATFORM``: pin the JAX backend before it initializes.

    Needed where the ambient environment force-registers an accelerator
    plugin that ignores ``JAX_PLATFORMS`` (and useful generally to run
    CPU-only control nodes next to TPU data nodes). Must run before any
    jax backend use; a no-op once a backend exists.
    """
    plat = os.environ.get("TFIDF_JAX_PLATFORM")
    if not plat:
        return
    import jax
    try:
        jax.config.update("jax_platforms", plat)
        n = int(os.environ.get("TFIDF_CPU_DEVICES", "0"))
        if plat == "cpu" and n > 0:
            jax.config.update("jax_num_cpu_devices", n)
    except RuntimeError as e:   # backend already initialized
        log.warning("platform override ignored", err=str(e))


def main(argv: list[str] | None = None) -> int:
    _apply_platform_override()
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())

"""Device mesh construction.

The reference's "mesh" is a set of pods discovered through ZooKeeper
(``registry/ServiceRegistry.java``) and addressed one HTTP call at a time.
Here the equivalent is a ``jax.sharding.Mesh`` over TPU devices with two
axes:

* ``docs``  — data parallelism over the corpus: each slice owns a disjoint
  set of documents, exactly like the reference's workers (its only
  parallelism axis, SURVEY.md §2). Collectives over this axis: ``psum`` of
  document frequencies (global IDF — an improvement the reference never
  had), ``all_gather`` of per-shard top-k.
* ``terms`` — intra-document parallelism over postings: one document's
  entries are split across devices and partial scores ``psum``-reduced.
  This is the sequence-parallel analog for this workload — it is what lets
  arbitrarily long documents / dense shards scale beyond one device's HBM,
  where the reference simply holds whole documents on one worker
  (SURVEY.md §5.7).

Multi-host: under ``jax.distributed.initialize`` the same mesh spans hosts;
``docs`` is laid out over DCN (independent shards, no intra-query traffic
except the final k-sized gather) and ``terms`` over ICI (per-query psum).
"""

from __future__ import annotations

import math

import jax
import numpy as np
from jax.sharding import Mesh


def default_mesh_shape(n_devices: int | None = None) -> tuple[int, int]:
    """(docs, terms) shape: favor the docs axis, keep terms a small power of 2.

    Scoring traffic per query over ``terms`` is a [B, doc_cap] psum, while
    ``docs`` shards are embarrassingly parallel — so docs-major is the right
    default, mirroring the scaling-book recipe of putting the cheap axis on
    the slower links.
    """
    n = n_devices if n_devices is not None else len(jax.devices())
    terms = 1
    while n % 2 == 0 and n // 2 >= 4 and terms < 2:
        # only fold into terms when there are plenty of devices
        n //= 2
        terms *= 2
    return (n, terms)


def make_mesh(shape: tuple[int, int] | None = None,
              axis_names: tuple[str, str] = ("docs", "terms"),
              devices: list | None = None) -> Mesh:
    devs = devices if devices is not None else jax.devices()
    if shape is None or not shape:
        shape = (len(devs), 1)
    if math.prod(shape) != len(devs):
        raise ValueError(f"mesh shape {shape} != {len(devs)} devices")
    arr = np.asarray(devs).reshape(shape)
    return Mesh(arr, axis_names)

"""Device mesh construction.

The reference's "mesh" is a set of pods discovered through ZooKeeper
(``registry/ServiceRegistry.java``) and addressed one HTTP call at a time.
Here the equivalent is a ``jax.sharding.Mesh`` over TPU devices with two
axes:

* ``docs``  — data parallelism over the corpus: each slice owns a disjoint
  set of documents, exactly like the reference's workers (its only
  parallelism axis, SURVEY.md §2). Collectives over this axis: ``psum`` of
  document frequencies (global IDF — an improvement the reference never
  had), ``all_gather`` of per-shard top-k.
* ``terms`` — intra-document parallelism over postings: one document's
  entries are split across devices and partial scores ``psum``-reduced.
  This is the sequence-parallel analog for this workload — it is what lets
  arbitrarily long documents / dense shards scale beyond one device's HBM,
  where the reference simply holds whole documents on one worker
  (SURVEY.md §5.7).

Multi-host: under ``jax.distributed.initialize`` the same mesh spans hosts;
``docs`` is laid out over DCN (independent shards, no intra-query traffic
except the final k-sized gather) and ``terms`` over ICI (per-query psum).
"""

from __future__ import annotations

import math

import jax
import numpy as np
from jax.sharding import Mesh

from tfidf_tpu.utils.logging import get_logger

log = get_logger("parallel.mesh")

_distributed_initialized = False


def initialize_multihost(coordinator_address: str | None = None,
                         num_processes: int | None = None,
                         process_id: int | None = None) -> bool:
    """Multi-host bootstrap over DCN — ``jax.distributed.initialize``
    (SURVEY.md §5.8's prescribed TPU-native equivalent of the reference's
    ZooKeeper-discovered pod set).

    On TPU pods every argument is auto-detected from the TPU metadata
    server, so ``serve --distributed`` needs no flags there. Elsewhere
    (GPU/CPU clusters, tests) pass them explicitly or set the standard
    ``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID``
    environment variables (read by jax itself).

    After this returns, ``jax.devices()`` spans all hosts and
    :func:`make_mesh` builds a global mesh — the ``docs`` axis rides DCN
    (embarrassingly parallel shards, one k-sized gather per query) and
    ``terms`` rides ICI (per-query psum), per the module docstring above.

    Idempotent: returns True only when this call performed the
    initialization.
    """
    global _distributed_initialized
    if _distributed_initialized:
        return False
    kw = {}
    if coordinator_address:
        kw["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kw["num_processes"] = num_processes
    if process_id is not None:
        kw["process_id"] = process_id
    jax.distributed.initialize(**kw)
    _distributed_initialized = True
    log.info("jax.distributed initialized",
             process=jax.process_index(), processes=jax.process_count(),
             devices=len(jax.devices()))
    return True


def default_mesh_shape(n_devices: int | None = None) -> tuple[int, int]:
    """(docs, terms) shape: favor the docs axis, keep terms a small power of 2.

    Scoring traffic per query over ``terms`` is a [B, doc_cap] psum, while
    ``docs`` shards are embarrassingly parallel — so docs-major is the right
    default, mirroring the scaling-book recipe of putting the cheap axis on
    the slower links.
    """
    n = n_devices if n_devices is not None else len(jax.devices())
    terms = 1
    while n % 2 == 0 and n // 2 >= 4 and terms < 2:
        # only fold into terms when there are plenty of devices
        n //= 2
        terms *= 2
    return (n, terms)


def make_mesh(shape: tuple[int, int] | None = None,
              axis_names: tuple[str, str] = ("docs", "terms"),
              devices: list | None = None) -> Mesh:
    devs = devices if devices is not None else jax.devices()
    if shape is None or not shape:
        shape = (len(devs), 1)
    if math.prod(shape) != len(devs):
        raise ValueError(f"mesh shape {shape} != {len(devs)} devices")
    arr = np.asarray(devs).reshape(shape)
    return Mesh(arr, axis_names)

"""Mesh-sharded dense top-k: the embedding column over the ``docs`` axis.

Single-process analog of the cluster's two-stage plan: the embedding
rows are sharded over the ``docs`` mesh axis by the SAME placement the
sparse postings use (each docs slice owns a disjoint, contiguous row
range — ``base`` carries each shard's global row offset, playing the
role of the owner map), every device computes its local blocked matmul
top-k (``ops/dense.py`` work, MXU-shaped per shard), and one k-sized
``all_gather`` + exact merge produces the global list.  Exact by the
same argument as the sparse gather: the global top-k is contained in
the union of per-shard top-ks.

Collective cost per query batch is O(D * B * k) — the k-sized gather
only, never the embeddings — so the ``docs`` axis rides DCN fine,
mirroring ``parallel/sharded.py``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tfidf_tpu.ops.topk import merge_topk, pack_topk
from tfidf_tpu.parallel._compat import shard_map as _shard_map


def shard_dense_column(mesh: Mesh, rows_per_shard: list,
                       dim_pad: int) -> tuple:
    """Place per-shard embedding rows onto the ``docs`` axis.

    Every shard is padded to the widest shard's row count (static
    shapes, as shard_map requires); ``num_live`` masks the padding and
    ``base`` maps local row ids back to global ids in the concatenated
    (shard-major) order — the order the caller's name table uses.
    Returns (emb, num_live, base) device arrays.
    """
    n_shards = int(mesh.shape["docs"])
    if len(rows_per_shard) != n_shards:
        raise ValueError(f"{len(rows_per_shard)} shards for a "
                         f"{n_shards}-wide docs axis")
    cap = max(1, max(r.shape[0] for r in rows_per_shard))
    emb = np.zeros((n_shards * cap, dim_pad), dtype=np.float32)
    live = np.zeros(n_shards, dtype=np.int32)
    base = np.zeros(n_shards, dtype=np.int32)
    off = 0
    for s, rows in enumerate(rows_per_shard):
        n = rows.shape[0]
        emb[s * cap:s * cap + n, :rows.shape[1]] = rows
        live[s] = n
        base[s] = off
        off += n
    dev = jax.device_put(emb, NamedSharding(mesh, P("docs", None)))
    live_d = jax.device_put(live, NamedSharding(mesh, P("docs")))
    base_d = jax.device_put(base, NamedSharding(mesh, P("docs")))
    return dev, live_d, base_d


def make_mesh_dense_search(mesh: Mesh, *, k: int):
    """Build the jitted sharded search: (queries [B, dim_pad]
    replicated, emb/num_live/base from :func:`shard_dense_column`) ->
    packed global top-k [B, 2k] replicated (``ops/topk.pack_topk``
    layout, ids in concatenated shard-major order)."""

    def step(queries, emb, num_live, base):
        cap = emb.shape[0]                      # per-shard rows
        scores = jax.lax.dot_general(
            queries, emb,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)
        idx = jnp.arange(cap, dtype=jnp.int32)[None, :]
        masked = jnp.where(idx < num_live[0], scores, -jnp.inf)
        kk = min(k, cap)
        vals, ids = jax.lax.top_k(masked, kk)
        gids = ids.astype(jnp.int32) + base[0]
        all_vals = jax.lax.all_gather(vals, "docs")     # [D, B, kk]
        all_ids = jax.lax.all_gather(gids, "docs")
        top_vals, top_ids = merge_topk(all_vals, all_ids)
        return pack_topk(top_vals, top_ids)

    sharded = _shard_map(
        step, mesh=mesh,
        in_specs=(P(None, None), P("docs", None), P("docs"), P("docs")),
        out_specs=P(None, None), check_vma=False)
    return jax.jit(sharded)

"""MeshEllIndex / MeshEllSearcher — ELL-base + COO-delta mesh serving.

The fast mesh layout (:mod:`tfidf_tpu.parallel.mesh_ell`): committed
documents live in a blocked-ELL base scored by the compare/MXU kernel;
appends land in a COO delta (the plain :class:`ShardedArrays` machinery)
and are folded into the base at the next re-shard — Lucene's
segments-then-merge shape at mesh scale. Global statistics (df, N,
avgdl) are recomputed over the LIVE corpus at every commit and pushed
replicated to the mesh, and base impacts are refreshed from them
on-device, so scores always reflect current stats (the streaming-segment
contract) and — unlike the COO path, which keeps tombstones in df until
a re-shard — match the single-device rebuild engine exactly.

Not supported here (Engine falls back to the COO mesh layout):
``tfidf_cosine`` (norms per doc per commit) and Lucene local-stats
parity / unbounded results (parity is a correctness mode; it keeps the
scatter path).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from tfidf_tpu.ops.csr import CooShard, next_capacity
from tfidf_tpu.ops.dfdelta import DfDeltaApplier
from tfidf_tpu.parallel.mesh_ell import (MeshEllArrays, build_mesh_ell,
                                         make_impact_refresh,
                                         make_mesh_ell_search,
                                         with_ell_live)
from tfidf_tpu.parallel.mesh_index import MeshIndex, MeshSearcher
from tfidf_tpu.parallel.sharded import (ShardedArrays,
                                        build_sharded_arrays,
                                        with_live_mask)
from tfidf_tpu.utils.logging import get_logger
from tfidf_tpu.utils.metrics import global_metrics

log = get_logger("parallel.mesh_ell_index")


class MeshEllSnapshot:
    """Published state: ELL base + COO delta + current global stats."""

    def __init__(self, *, base: MeshEllArrays, delta: ShardedArrays,
                 perms, base_counts, shard_docs, df_g, n_docs, avgdl,
                 version, nnz, total_live) -> None:
        self.base = base
        self.delta = delta
        self.perms = perms                 # per shard: ell_row -> ins id
        self.base_counts = base_counts     # docs in base per shard
        self.shard_docs = shard_docs
        self.df_g = df_g                   # f32 [vocab_cap] replicated
        self.n_docs = n_docs               # f32 scalar (LIVE count)
        self.avgdl = avgdl
        self.version = version
        self.nnz = nnz
        self.total_live = total_live

    @property
    def stride(self) -> int:
        return self.base.doc_cap + self.delta.doc_cap

    def name_of(self, gid: int) -> str | None:
        s, local = divmod(gid, self.stride)
        if s >= len(self.shard_docs):
            return None
        sd = self.shard_docs[s]
        if local < self.base.doc_cap:      # ELL row -> permuted ins id
            perm = self.perms[s]
            if local >= perm.shape[0]:
                return None
            return sd[int(perm[local])].name
        delta_local = local - self.base.doc_cap
        ins = self.base_counts[s] + delta_local
        return sd[ins].name if ins < len(sd) else None


class MeshEllIndex(MeshIndex):
    """MeshIndex whose committed base is blocked ELL (the fast layout)."""

    def __init__(self, model, mesh=None, min_doc_cap: int = 1024,
                 min_chunk_cap: int = 1 << 14,
                 ell_width_cap: int = 256,
                 delta_rebuild_frac: float = 0.5,
                 incremental_stats: bool = True) -> None:
        super().__init__(model, mesh=mesh, min_doc_cap=min_doc_cap,
                         min_chunk_cap=min_chunk_cap)
        self.ell_width_cap = ell_width_cap
        # fold the delta into the base when it exceeds this fraction of
        # the corpus (the merge policy)
        self.delta_rebuild_frac = delta_rebuild_frac
        # False = the pre-incremental control path: every commit
        # recomputes df/N/avgdl from the live host postings (O(corpus
        # nnz)) and re-uploads the dense df — kept as the bench.py
        # --kernel old-vs-new lever, never the default
        self.incremental_stats = incremental_stats
        self._base: MeshEllArrays | None = None
        self._perms: list[np.ndarray] = []
        self._base_counts: list[int] = []
        self._refresh_fn = None
        # incremental live-corpus stats, maintained on every mutation so
        # commit is O(batch) host-side (full recompute only on rebuild)
        self._df_live = np.zeros(0, np.float64)
        self._n_live_stat = 0
        self._len_sum_stat = 0.0
        # journal of df changes since the last commit, O(1) per
        # mutation — the commit applies them as ONE sparse on-device
        # scatter into the replicated df instead of re-uploading the
        # whole [vocab_cap] array (2MB at 500k terms, the dominant
        # steady-commit cost on high-latency links)
        self._df_delta = DfDeltaApplier(
            NamedSharding(self.mesh, P(None)))
        # witness: commits that paid the O(corpus nnz) host stat
        # recompute (rebuild resync / vocab growth / the control path).
        # Steady-state append/delete commits must leave it untouched —
        # tests/test_commit_stats.py pins that.
        self.df_full_recomputes = 0
        # append traffic observed (attempted, not just succeeded —
        # a first burst bigger than the floor delta overflows BEFORE
        # any append succeeds): gates `_empty_delta`'s threshold
        # sizing so read-mostly indexes never reserve delta HBM
        self._append_attempts = 0

    # ---- incremental stats bookkeeping ----

    def _stat_add(self, entry) -> None:
        ids = entry.term_ids
        if ids.shape[0]:
            hi = int(ids.max()) + 1
            if hi > self._df_live.shape[0]:
                grown = np.zeros(max(hi, 2 * self._df_live.shape[0]),
                                 np.float64)
                grown[:self._df_live.shape[0]] = self._df_live
                self._df_live = grown
            np.add.at(self._df_live, ids, 1.0)
            self._df_delta.record(ids, 1.0)
        self._n_live_stat += 1
        self._len_sum_stat += entry.length

    def _stat_remove(self, entry) -> None:
        ids = entry.term_ids
        if ids.shape[0]:
            np.add.at(self._df_live, ids, -1.0)
            self._df_delta.record(ids, -1.0)
        self._n_live_stat -= 1
        self._len_sum_stat -= entry.length

    def add_document_arrays(self, name, ids, tfs, length=None):
        from tfidf_tpu.engine.index import (DocEntry,
                                            check_sorted_unique_ids)
        tfs = np.asarray(tfs, np.float32)
        ids = np.asarray(ids, np.int32)
        check_sorted_unique_ids(name, ids)
        entry = DocEntry(
            name=name, term_ids=ids, tfs=tfs,
            length=float(length if length is not None else tfs.sum()))
        with self._write_lock:
            old = self._pending.get(name)
            if old is not None:
                self._stat_remove(old)       # replaced in place
            else:
                placed = self._placed.pop(name, None)
                if placed is not None:       # upsert: tombstone old copy
                    s, local = placed
                    self._shard_docs[s][local].live = False
                    self._stat_remove(self._shard_docs[s][local])
                    self._mask_dirty = True
            self._pending[name] = entry
            self._stat_add(entry)
            self._gen += 1
        global_metrics.inc("docs_indexed")

    def _bulk_load_stats(self, term_ids, lengths) -> None:
        # vectorized resync: one bincount instead of a per-doc
        # _stat_add loop (the very loop bulk_load_packed removes). The
        # first commit takes the rebuild path (no base yet) and
        # re-syncs from the authoritative postings regardless; the
        # single journal entry keeps the invariant for safety.
        ids = term_ids.astype(np.int64)
        hi = int(ids.max()) + 1 if ids.size else 1
        self._df_live = np.bincount(ids, minlength=hi).astype(np.float64)
        self._n_live_stat = int(lengths.shape[0])
        self._len_sum_stat = float(np.asarray(lengths,
                                              np.float64).sum())
        self._df_delta.clear()
        self._df_delta.record(term_ids, 1.0)

    def delete_document(self, name: str) -> bool:
        with self._write_lock:
            entry = self._pending.pop(name, None)
            if entry is not None:
                self._stat_remove(entry)
                self._gen += 1
                return True
            placed = self._placed.pop(name, None)
            if placed is None:
                return False
            s, local = placed
            self._shard_docs[s][local].live = False
            self._stat_remove(self._shard_docs[s][local])
            self._mask_dirty = True
            self._gen += 1
            return True

    # ---- commit ----

    def commit(self, vocab_cap: int):
        with self._write_lock:
            gen0 = self._gen
            if (self._committed_gen == gen0 and self.snapshot is not None
                    and self.snapshot.df_g.shape[0] >= vocab_cap):
                return self.snapshot
            pending = list(self._pending.values())
            delta = self.snapshot.delta if self.snapshot else None
            need_rebuild = (
                self._base is None
                or vocab_cap > self.snapshot.df_g.shape[0]
                or self._delta_too_big(pending))
            if need_rebuild:
                self._rebuild_ell_locked(pending, vocab_cap)
                delta = self._empty_delta(vocab_cap)
            elif pending:
                try:
                    delta = self._append_locked(delta, pending)
                except ValueError as e:
                    log.info("delta overflow; folding into ELL base",
                             reason=str(e).split(";")[0])
                    self._rebuild_ell_locked(pending, vocab_cap)
                    delta = self._empty_delta(vocab_cap)
            self._pending = {}

            # live-corpus global stats (appends and deletes both move
            # them; the base impacts are refreshed below so IDF never
            # goes stale). After a rebuild the replicated df is uploaded
            # whole; otherwise the journaled changes land as one sparse
            # on-device scatter (O(touched terms), not O(vocab)).
            if not self.incremental_stats:
                # control path: full O(corpus nnz) recompute + dense
                # re-upload every commit (the pre-r14 cost model)
                df_host, n_live, len_sum = self._live_stats_scratch(
                    vocab_cap, include_pending=False)
                self.df_full_recomputes += 1
                df_g = jax.device_put(
                    df_host, NamedSharding(self.mesh, P(None)))
                self._df_delta.clear()
            elif need_rebuild or self.snapshot is None:
                df_host, n_live, len_sum = self._live_stats(vocab_cap)
                df_g = jax.device_put(
                    df_host, NamedSharding(self.mesh, P(None)))
                self._df_delta.clear()
            else:
                df_g = self._df_delta.apply(self.snapshot.df_g)
                n_live = self._n_live_stat
                len_sum = self._len_sum_stat
            n_docs = jnp.float32(n_live)
            avgdl = jnp.float32(len_sum / n_live if n_live else 1.0)
            if self._refresh_fn is None:
                kw = self.model.score_kwargs()
                self._refresh_fn = make_impact_refresh(
                    self.mesh, model=kw["model"], k1=kw.get("k1", 1.2),
                    b=kw.get("b", 0.75))
            base = self._refresh_fn(self._base, df_g, n_docs, avgdl)
            # liveness only changes on delete/upsert (appends never touch
            # it, rebuilds drop tombstones and build a fresh all-live
            # mask) — rebuilding the masks every commit was an O(corpus)
            # host loop on the serving path (ADVICE r2, medium)
            if self._mask_dirty:
                base = with_ell_live(self.mesh, base,
                                     self._ell_mask(base))
                delta = with_live_mask(self.mesh, delta,
                                       self._delta_mask(delta.doc_cap))
                self._mask_dirty = False
            self._base = base
            self._version += 1
            snap = MeshEllSnapshot(
                base=base, delta=delta, perms=self._perms,
                base_counts=list(self._base_counts),
                shard_docs=self._shard_docs,
                df_g=df_g, n_docs=n_docs, avgdl=avgdl,
                version=self._version, nnz=self.nnz_live,
                total_live=len(self._placed))
            self.snapshot = snap
            self._committed_gen = gen0
        global_metrics.set_gauge("index_docs", snap.total_live)
        global_metrics.set_gauge("index_nnz", snap.nnz)
        log.info("committed mesh-ell snapshot", version=snap.version,
                 docs=snap.total_live, nnz=snap.nnz,
                 mesh=dict(self.mesh.shape))
        return snap

    def _delta_too_big(self, pending) -> bool:
        base_docs = sum(self._base_counts)
        delta_docs = (len(self._placed) + len(pending)) - base_docs
        return (base_docs == 0
                or delta_docs > self.delta_rebuild_frac * base_docs)

    def _live_stats(self, vocab_cap: int):
        """O(vocab) snapshot of the incrementally-maintained live stats
        (df counts are integers, so the float64 accumulators are exact;
        rebuilds resync from scratch as a belt)."""
        df = np.zeros(vocab_cap, np.float32)
        n = min(self._df_live.shape[0], vocab_cap)
        df[:n] = self._df_live[:n]
        return df, self._n_live_stat, self._len_sum_stat

    def _live_stats_scratch(self, vocab_cap: int,
                            include_pending: bool = True):
        """Full recompute over live postings (rebuild resync + tests).
        ``include_pending=False`` when pending was already merged into
        the shard lists (mid-rebuild)."""
        ids = []
        n = 0
        len_sum = 0.0
        for sd in self._shard_docs:
            for d in sd:
                if d.live:
                    ids.append(d.term_ids)
                    n += 1
                    len_sum += d.length
        if include_pending:
            for d in self._pending.values():
                ids.append(d.term_ids)
                n += 1
                len_sum += d.length
        if ids:
            allids = np.concatenate(ids)
            df = np.bincount(allids, minlength=vocab_cap)[:vocab_cap]
            df = df.astype(np.float32)
        else:
            df = np.zeros(vocab_cap, np.float32)
        return df, n, len_sum

    def _rebuild_ell_locked(self, pending, vocab_cap: int) -> None:
        """Fold everything (base + delta + pending) into a fresh ELL
        base with round-robin placement; drops tombstones."""
        entries = []
        for sd in self._shard_docs:
            entries.extend(d for d in sd if d.live)
        entries.extend(pending)
        per_shard = [[] for _ in range(self.D)]
        shard_docs = [[] for _ in range(self.D)]
        placed = {}
        for i, e in enumerate(entries):
            e.live = True
            s = i % self.D
            placed[e.name] = (s, len(shard_docs[s]))
            shard_docs[s].append(e)
            per_shard[s].append(e)
        # build FIRST; install the new placement only once the device
        # build succeeded — a failed build (OOM) must not leave _placed
        # pointing into arrays that were never installed (ADVICE r2)
        base, perms = build_mesh_ell(
            per_shard, self.mesh, self.model.transform_doc_len,
            width_cap=self.ell_width_cap,
            min_rows=min(256, self.min_doc_cap))
        self._shard_docs = shard_docs
        self._placed = placed
        self._base = base
        self._perms = perms
        self._base_counts = [len(p) for p in per_shard]
        self._mask_dirty = False
        # resync the incremental stats from the authoritative postings
        # (pending was just merged into the shard lists above) — the
        # one O(corpus nnz) pass steady commits never take (witness)
        self.df_full_recomputes += 1
        df, n, len_sum = self._live_stats_scratch(
            max(vocab_cap, self._df_live.shape[0], 1),
            include_pending=False)
        self._df_live = df.astype(np.float64)
        self._n_live_stat = n
        self._len_sum_stat = len_sum
        self.rebuilds += 1
        global_metrics.inc("mesh_reshards")

    def _empty_delta(self, vocab_cap: int) -> ShardedArrays:
        """Fresh COO delta. For an index that has OBSERVED appends, it
        is sized to cover the MERGE POLICY's fold threshold
        (delta_rebuild_frac x the base corpus): before r14 the delta
        was floored at 256 docs/shard regardless of corpus size, so
        sustained append streams hit CAPACITY overflow — an unplanned
        O(corpus) rebuild — every ~256 docs/shard, long before the
        planned fold; steady-state commits were only nominally
        O(batch). Threshold sizing means the planned `_delta_too_big`
        fold is what ends a delta's life, and every commit in between
        is a pure O(batch) device append + sparse df scatter. HBM
        cost: the delta's COO arrays scale with delta_rebuild_frac x
        corpus nnz (~12B/entry across the terms axis) — so a
        READ-MOSTLY index (appends == 0 so far: bulk-load-and-serve)
        keeps the small floor delta and reserves nothing; the first
        append burst pays ONE amortized overflow rebuild to promote to
        threshold sizing."""
        min_doc = min(256, self.min_doc_cap)
        min_chunk = self.min_chunk_cap
        if self._append_attempts:
            base_docs = sum(self._base_counts)
            per_shard_docs = -(-int(base_docs * self.delta_rebuild_frac)
                               // max(self.D, 1))
            per_slice_nnz = -(-int(self.nnz_live
                                   * self.delta_rebuild_frac)
                              // max(self.D * self.T, 1))
            min_doc = max(min_doc,
                          next_capacity(per_shard_docs + 1, min_doc))
            min_chunk = max(min_chunk,
                            next_capacity(max(per_slice_nnz, 1),
                                          1 << 10))
        coo = CooShard(
            tf=np.zeros(0, np.float32), term=np.zeros(0, np.int32),
            doc=np.zeros(0, np.int32),
            doc_len=np.zeros(0, np.float32),
            df=np.zeros(vocab_cap, np.float32), nnz=0, num_docs=0)
        return build_sharded_arrays(
            coo, self.mesh, min_chunk_cap=min_chunk,
            min_doc_cap=min_doc)

    def _append_locked(self, delta: ShardedArrays,
                       pending) -> ShardedArrays:
        """Append into the COO delta. Placement slots continue after the
        base: insertion-local id = base_count + delta slot."""
        self._append_attempts += 1
        # reuse the parent's machinery; it reads/updates _shard_docs and
        # _placed with insertion-local ids, and build_ingest_batch's
        # local ids continue from delta.n_live — these agree because
        # delta slot = insertion id - base_count (appends only)
        loads = [sum(d.term_ids.nbytes + d.tfs.nbytes
                     for d in sd if d.live) for sd in self._shard_docs]
        slots = [len(sd) - bc for sd, bc in
                 zip(self._shard_docs, self._base_counts)]
        per_entries = [[] for _ in range(self.D)]
        for e in pending:
            s = int(np.argmin(loads))
            per_entries[s].append(e)
            loads[s] += e.term_ids.nbytes + e.tfs.nbytes
            slots[s] += 1
            if slots[s] > delta.doc_cap:
                raise ValueError("delta over doc capacity; re-shard")
        from tfidf_tpu.parallel.sharded import (build_ingest_batch,
                                                make_sharded_ingest)
        per_docs = [[dict(zip(e.term_ids.tolist(),
                              e.tfs.astype(np.float64).tolist()))
                     for e in es] for es in per_entries]
        per_lens = [
            list(self.model.transform_doc_len(
                np.asarray([e.length for e in es], np.float32))
                .astype(np.float32)) if es else []
            for es in per_entries]
        per_raw = [[e.length for e in es] for es in per_entries]
        max_entries = max((sum(e.term_ids.shape[0] for e in es)
                           for es in per_entries), default=0)
        C = next_capacity(max(-(-max_entries // self.T), 1), 64)
        batch = build_ingest_batch(self.mesh, delta, per_docs, per_lens,
                                   C, raw_lengths_per_shard=per_raw)
        if self._ingest_fn is None:
            make = make_sharded_ingest
            self._ingest_fn = make(self.mesh)
        delta = self._ingest_fn(delta, *batch)
        for s, es in enumerate(per_entries):
            for e in es:
                self._placed[e.name] = (s, len(self._shard_docs[s]))
                self._shard_docs[s].append(e)
        self.appends += 1
        global_metrics.inc("mesh_appends")
        return delta

    # ---- masks ----

    def _ell_mask(self, base: MeshEllArrays) -> np.ndarray:
        mask = np.zeros((self.D, base.doc_cap), np.float32)
        for s, (perm, bc) in enumerate(zip(self._perms,
                                           self._base_counts)):
            if not bc:
                continue
            live = np.fromiter((d.live for d in self._shard_docs[s][:bc]),
                               np.float32, bc)
            mask[s, :perm.shape[0]] = live[perm]
        return mask

    def _delta_mask(self, doc_cap: int) -> np.ndarray:
        mask = np.zeros((self.D, doc_cap), np.float32)
        for s, bc in enumerate(self._base_counts):
            sd = self._shard_docs[s]
            n = len(sd) - bc
            if n:
                mask[s, :n] = np.fromiter((d.live for d in sd[bc:]),
                                          np.float32, n)
        return mask



class MeshEllSearcher(MeshSearcher):
    """MeshSearcher over the ELL base + delta snapshot."""

    # Hard cap on corpus size for the unbounded parity fallback: the
    # fallback rebuilds a full duplicate COO MeshIndex (host loop over
    # every live doc + a device commit) and roughly doubles HBM
    # residency while cached. That is fine as a correctness tool at
    # test scale, but a stray ``unbounded=True`` against a large
    # serving engine must fail fast instead of stalling the node for
    # minutes. Raise the attribute explicitly on a searcher instance to
    # opt in to a bigger parity replay.
    unbounded_parity_max_docs: int = 200_000

    def _get_search_fn(self, k: int):
        fn = self._search_fns.get(k)
        if fn is None:
            fn = make_mesh_ell_search(
                self.index.mesh, k=k,
                model=self.model.score_kwargs()["model"],
                a_build=self.kernel_a_build,
                packed=True, **self._model_kwargs())
            self._search_fns[k] = fn
        return fn

    def _on_snapshot(self, snap) -> None:
        # the parity-fallback cache pins a full device-resident COO copy
        # of the corpus; release it as soon as the snapshot advances
        # instead of holding stale HBM until the next unbounded call
        cached = getattr(self, "_unbounded_cache", None)
        if cached is not None and (snap is None
                                   or cached[0] != snap.version):
            self._unbounded_cache = None

    def _dispatch_chunk(self, snap, qb, k: int):
        kk = min(k, snap.stride)
        return self._get_search_fn(kk)(
            snap.base, snap.delta, snap.df_g, snap.n_docs,
            snap.avgdl, qb), kk

    def _search_unbounded(self, snap, queries, k):
        # the ELL base cannot rank every matching document (its row
        # space is permuted and lives behind top-k); serve parity
        # requests by scoring the same live postings through a COO mesh
        # engine instead of erroring (VERDICT r2 weak #8)
        return self._search_unbounded_coo(snap, queries, k)

    def _search_unbounded_coo(self, snap, queries, k):
        """Per-call parity fallback (VERDICT r2 weak #8): replay the
        COMMITTED snapshot's postings into a COO mesh index and rank
        every match there. Slow by design — parity mode is a correctness
        tool, not the serving path — but a per-request ``unbounded=True``
        must not 500. The document set comes from the snapshot's own
        device live masks (not the mutable index state), so unbounded
        and bounded answers on the same searcher agree even with
        uncommitted writes in flight. The throwaway searcher is cached
        by snapshot version — parity harnesses issuing many unbounded
        calls against one snapshot pay the O(corpus) replay once."""
        from tfidf_tpu.parallel.mesh_index import MeshIndex, MeshSearcher

        cached = getattr(self, "_unbounded_cache", None)
        if cached is not None and cached[0] == snap.version:
            return cached[1].search(queries, k=k, unbounded=True)
        total_live = int(np.sum(np.asarray(snap.n_docs)))
        if total_live > self.unbounded_parity_max_docs:
            raise ValueError(
                f"unbounded=True parity fallback refused: snapshot holds "
                f"{total_live} live docs > cap "
                f"{self.unbounded_parity_max_docs}. The fallback rebuilds "
                f"a duplicate COO index (O(corpus) host replay + ~2x HBM); "
                f"it is a parity/testing tool, not a serving path. Set "
                f"searcher.unbounded_parity_max_docs explicitly to opt in.")
        base_live = np.asarray(snap.base.live)       # [D, doc_cap_ell]
        delta_live = np.asarray(snap.delta.live)     # [D, doc_cap_delta]
        delta_n = np.asarray(snap.delta.n_live)      # [D]
        entries = []  # snapshot-live docs, reconstructed from the masks
        for s, sd in enumerate(snap.shard_docs):
            perm, bc = snap.perms[s], snap.base_counts[s]
            for ell_row in range(perm.shape[0]):
                if base_live[s, ell_row] > 0:
                    entries.append(sd[int(perm[ell_row])])
            for slot in range(int(delta_n[s])):
                if delta_live[s, slot] > 0:
                    entries.append(sd[bc + slot])
        idx = MeshIndex(self.index.model, mesh=self.index.mesh,
                        min_doc_cap=self.index.min_doc_cap,
                        min_chunk_cap=self.index.min_chunk_cap)
        for e in entries:
            idx.add_document_arrays(e.name, e.term_ids, e.tfs, e.length)
        idx.commit(max(self.vocab.capacity(), 1))
        searcher = MeshSearcher(
            idx, self.analyzer, self.vocab, self.model,
            query_batch=self.query_batch,
            max_query_terms=self.max_query_terms,
            top_k=self.top_k, result_order=self.result_order)
        self._unbounded_cache = (snap.version, searcher)
        return searcher.search(queries, k=k, unbounded=True)

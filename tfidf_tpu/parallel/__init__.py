from tfidf_tpu.parallel.mesh import make_mesh, default_mesh_shape
from tfidf_tpu.parallel.sharded import (
    ShardedArrays,
    build_sharded_arrays,
    build_ingest_batch,
    make_sharded_search,
    make_sharded_ingest,
    global_stats,
)

__all__ = [
    "make_mesh",
    "default_mesh_shape",
    "ShardedArrays",
    "build_sharded_arrays",
    "build_ingest_batch",
    "make_sharded_search",
    "make_sharded_ingest",
    "global_stats",
]

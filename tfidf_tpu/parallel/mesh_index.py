"""MeshIndex / MeshSearcher — the mesh-sharded SERVING path.

This is the production face of :mod:`tfidf_tpu.parallel.sharded`: a live
index whose committed state is :class:`ShardedArrays` on a
``("docs", "terms")`` device mesh, with the same write API as
:class:`~tfidf_tpu.engine.index.ShardIndex` so the whole Engine surface
(ingest, upload, checkpoint, cluster node) works unchanged on top of it.
One node hosting a MeshIndex subsumes the reference's entire worker pool:
what the Java system does with N HTTP workers and a scatter-gather leader
(``Leader.java:39-92``) happens here inside one jitted ``shard_map``
program — per-shard scoring, ``psum`` global IDF, terms-axis score reduce,
``all_gather`` distributed top-k — with collectives on ICI instead of JSON
over the network.

Lifecycle (the mesh analog of Lucene's segment/commit model,
``Worker.java:88,138``):

* **commit** publishes an immutable :class:`MeshSnapshot`. New documents
  append on-device (``make_sharded_ingest`` — dynamic-update-slice at the
  shard cursors, O(batch)); placement is least-loaded-shard by live
  postings bytes, the ``index-size`` balancing policy of
  ``Leader.java:168-189`` applied at mesh scale.
* **deletes/upserts** tombstone via the snapshot's live mask (Lucene's
  deleted-docs bitmap); postings stay, df/avgdl keep counting them until
  the next re-shard, like Lucene until merge.
* **growth**: when the vocabulary outgrows ``vocab_cap`` or a capacity
  bucket overflows, the index re-shards — a full rebuild from the retained
  host postings onto the same mesh with wider buckets (capacities are
  power-of-two bucketed with headroom, so this is rare and amortized).
* **recovery**: host postings are the source of truth; the device state is
  always reconstructible (recovery-by-rebuild, ``Worker.java:77-88``).

Thread safety: single-writer lock over mutations + commit; searches are
lock-free against a published snapshot. Snapshots stay valid across later
commits because appends only extend per-shard doc lists and rebuilds swap
in fresh list objects — an old snapshot keeps references to the lists it
was built from.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

import numpy as np

from tfidf_tpu.engine.index import DocEntry
from tfidf_tpu.models.base import ScoringModel
from tfidf_tpu.ops.csr import CooShard, next_capacity
from tfidf_tpu.parallel.mesh import make_mesh
from tfidf_tpu.parallel.sharded import (ShardedArrays, build_ingest_batch,
                                        build_sharded_arrays,
                                        make_sharded_ingest,
                                        make_sharded_scores,
                                        make_sharded_search, with_live_mask)
from tfidf_tpu.utils.logging import get_logger
from tfidf_tpu.utils.metrics import global_metrics

log = get_logger("parallel.mesh_index")


@dataclass
class MeshSnapshot:
    """Immutable published state: device arrays + the name mapping."""
    arrays: ShardedArrays
    shard_docs: list      # list[list[DocEntry]] — append-only per shard
    version: int
    nnz: int
    total_live: int

    def name_of(self, gid: int) -> str | None:
        """Global id (docs_shard * doc_cap + local) -> document name."""
        doc_cap = self.arrays.doc_cap
        sd = self.shard_docs[gid // doc_cap]
        local = gid % doc_cap
        return sd[local].name if local < len(sd) else None


class MeshIndex:
    """Mesh-resident shard index with the ShardIndex write API."""

    def __init__(self, model: ScoringModel,
                 mesh=None,
                 min_doc_cap: int = 1024,
                 min_chunk_cap: int = 1 << 14) -> None:
        self.model = model
        self.mesh = mesh if mesh is not None else make_mesh()
        self.D = self.mesh.shape["docs"]
        self.T = self.mesh.shape["terms"]
        self.min_doc_cap = min_doc_cap
        self.min_chunk_cap = min_chunk_cap
        self._write_lock = threading.Lock()
        # committed docs per shard in local-id order (tombstones included —
        # a slot is never reused until a re-shard)
        self._shard_docs: list[list[DocEntry]] = [[] for _ in range(self.D)]
        self._placed: dict[str, tuple[int, int]] = {}
        self._pending: dict[str, DocEntry] = {}   # upsert: latest wins
        self._mask_dirty = False
        self._gen = 1
        self._committed_gen = 0
        self._version = 0
        self.snapshot: MeshSnapshot | None = None
        self._ingest_fn = None
        # observable lifecycle counters (tests + /api/metrics)
        self.rebuilds = 0
        self.appends = 0

    # ---- write path (ShardIndex-compatible) ----

    def add_document(self, name: str, id_counts: dict[int, int],
                     length: float | None = None) -> None:
        if id_counts:
            items = sorted(id_counts.items())
            ids = np.fromiter((t for t, _ in items), np.int32, len(items))
            tfs = np.fromiter((f for _, f in items), np.float32,
                              len(items))
        else:
            ids = np.empty(0, np.int32)
            tfs = np.empty(0, np.float32)
        self.add_document_arrays(name, ids, tfs, length)

    def add_document_arrays(self, name: str, ids: np.ndarray,
                            tfs: np.ndarray,
                            length: float | None = None) -> None:
        from tfidf_tpu.engine.index import check_sorted_unique_ids
        tfs = np.asarray(tfs, np.float32)
        ids = np.asarray(ids, np.int32)
        check_sorted_unique_ids(name, ids)
        entry = DocEntry(
            name=name, term_ids=ids, tfs=tfs,
            length=float(length if length is not None else tfs.sum()))
        with self._write_lock:
            placed = self._placed.pop(name, None)
            if placed is not None:   # upsert: tombstone the committed copy
                s, local = placed
                self._shard_docs[s][local].live = False
                self._mask_dirty = True
            self._pending[name] = entry
            self._gen += 1
        global_metrics.inc("docs_indexed")

    def bulk_load_packed(self, names, offsets, term_ids, tfs,
                         lengths) -> None:
        """Checkpoint-restore fast path: register the packed doc table
        as pending upserts in one pass (per-doc numpy VIEWS, no
        per-document ingest work); the next commit builds the sharded
        arrays in ONE vectorized rebuild. Placement is re-derived
        (round-robin) — scoring is placement-invariant because df/IDF
        are globalized by psum; only parity mode's per-shard statistics
        can differ from the pre-checkpoint placement."""
        from tfidf_tpu.engine.index import entries_from_packed
        entries, (offsets, term_ids, tfs, lengths) = \
            entries_from_packed(names, offsets, term_ids, tfs, lengths)
        with self._write_lock:
            if self._pending or self._placed or any(self._shard_docs):
                raise ValueError(
                    "bulk_load_packed requires an empty index")
            self._pending = {e.name: e for e in entries}
            if len(self._pending) != len(entries):
                self._pending = {}
                raise ValueError("bulk_load_packed: duplicate names")
            self._bulk_load_stats(term_ids, lengths)
            self._gen += 1
        global_metrics.inc("docs_indexed", len(entries))

    def _bulk_load_stats(self, term_ids, lengths) -> None:
        """Hook for subclasses with incremental stat accumulators
        (caller holds the write lock)."""

    def delete_document(self, name: str) -> bool:
        with self._write_lock:
            if self._pending.pop(name, None) is not None:
                self._gen += 1
                return True
            placed = self._placed.pop(name, None)
            if placed is None:
                return False
            s, local = placed
            self._shard_docs[s][local].live = False
            self._mask_dirty = True
            self._gen += 1
            return True

    # ---- stats ----

    @property
    def num_live_docs(self) -> int:
        return len(self._placed) + len(self._pending)

    @property
    def nnz_live(self) -> int:
        n = sum(d.term_ids.shape[0] for d in self._pending.values())
        for sd in self._shard_docs:
            n += sum(d.term_ids.shape[0] for d in sd if d.live)
        return int(n)

    def size_bytes(self) -> int:
        n = sum(d.term_ids.nbytes + d.tfs.nbytes
                for d in self._pending.values())
        for sd in self._shard_docs:
            n += sum(d.term_ids.nbytes + d.tfs.nbytes
                     for d in sd if d.live)
        return int(n)

    def live_entries(self) -> list[DocEntry]:
        with self._write_lock:
            out = []
            for sd in self._shard_docs:
                out.extend(d for d in sd if d.live)
            out.extend(self._pending.values())
            return out


    # ---- commit ----

    def commit(self, vocab_cap: int) -> MeshSnapshot:
        with self._write_lock:
            gen0 = self._gen
            if (self._committed_gen == gen0 and self.snapshot is not None
                    and self.snapshot.arrays.vocab_cap >= vocab_cap):
                return self.snapshot
            pending = list(self._pending.values())
            arrays = self.snapshot.arrays if self.snapshot else None
            if arrays is None or vocab_cap > arrays.vocab_cap:
                arrays = self._rebuild_locked(pending, vocab_cap)
            elif pending:
                try:
                    arrays = self._append_locked(arrays, pending)
                except ValueError as e:
                    # a capacity bucket overflowed: re-shard with wider
                    # buckets (the analog of Lucene growing a new segment
                    # generation; amortized by power-of-two headroom)
                    log.info("capacity overflow; re-sharding",
                             reason=str(e).split(";")[0])
                    arrays = self._rebuild_locked(pending, vocab_cap)
            if self._mask_dirty:
                arrays = with_live_mask(self.mesh, arrays,
                                        self._host_mask(arrays.doc_cap))
                self._mask_dirty = False
            self._pending = {}
            self._version += 1
            snap = MeshSnapshot(
                arrays=arrays, shard_docs=self._shard_docs,
                version=self._version, nnz=self.nnz_live,
                total_live=len(self._placed))
            self.snapshot = snap
            self._committed_gen = gen0
        global_metrics.set_gauge("index_docs", snap.total_live)
        global_metrics.set_gauge("index_nnz", snap.nnz)
        global_metrics.set_gauge("mesh_rebuilds", self.rebuilds)
        log.info("committed mesh snapshot", version=snap.version,
                 docs=snap.total_live, nnz=snap.nnz,
                 mesh=dict(self.mesh.shape))
        return snap

    def _host_mask(self, doc_cap: int) -> np.ndarray:
        mask = np.zeros((self.D, doc_cap), np.float32)
        for s, sd in enumerate(self._shard_docs):
            for local, d in enumerate(sd):
                if d.live:
                    mask[s, local] = 1.0
        return mask

    def _entries_to_coo(self, entries: list[DocEntry], vocab_cap: int
                        ) -> tuple[CooShard, np.ndarray]:
        """Concatenation-order COO (NOT length-sorted — placement is
        ``i % D``, so order IS the layout; cf. ``shard_documents``).
        Returns (coo with model-transformed lengths, raw lengths)."""
        n = len(entries)
        sizes = np.fromiter((d.term_ids.shape[0] for d in entries),
                            np.int64, n)
        nnz = int(sizes.sum())
        tf = np.zeros(max(nnz, 1), np.float32)
        term = np.zeros(max(nnz, 1), np.int32)
        doc = np.zeros(max(nnz, 1), np.int32)
        if nnz:
            tf[:nnz] = np.concatenate([d.tfs for d in entries])
            term[:nnz] = np.concatenate([d.term_ids for d in entries])
            doc[:nnz] = np.repeat(np.arange(n, dtype=np.int32), sizes)
        df = (np.bincount(term[:nnz], minlength=vocab_cap)[:vocab_cap]
              .astype(np.float32) if nnz
              else np.zeros(vocab_cap, np.float32))
        raw_len = np.fromiter((d.length for d in entries), np.float32, n)
        doc_len = self.model.transform_doc_len(raw_len).astype(np.float32)
        return CooShard(tf=tf[:nnz], term=term[:nnz], doc=doc[:nnz],
                        doc_len=doc_len, df=df, nnz=nnz,
                        num_docs=n), raw_len

    def _rebuild_locked(self, pending: list[DocEntry],
                        vocab_cap: int) -> ShardedArrays:
        """Full re-shard from host postings: drops tombstones, re-tightens
        df, widens capacity buckets — the compaction/merge analog."""
        entries = []
        for sd in self._shard_docs:
            entries.extend(d for d in sd if d.live)
        entries.extend(pending)
        coo, raw_len = self._entries_to_coo(entries, vocab_cap)
        arrays = build_sharded_arrays(
            coo, self.mesh, min_chunk_cap=self.min_chunk_cap,
            min_doc_cap=self.min_doc_cap, raw_doc_len=raw_len)
        # fresh list objects: snapshots taken before this rebuild keep the
        # old lists (and the old arrays), staying internally consistent
        self._shard_docs = [[] for _ in range(self.D)]
        self._placed = {}
        for i, e in enumerate(entries):
            e.live = True
            s = i % self.D
            self._placed[e.name] = (s, len(self._shard_docs[s]))
            self._shard_docs[s].append(e)
        self._mask_dirty = False
        self.rebuilds += 1
        global_metrics.inc("mesh_reshards")
        return arrays

    def _append_locked(self, arrays: ShardedArrays,
                       pending: list[DocEntry]) -> ShardedArrays:
        """On-device append of the pending batch (O(batch), no rebuild).

        Placement: least-loaded shard by live postings bytes — the
        ``GET /worker/index-size`` balancing policy (``Leader.java:168-
        189``) applied per document at mesh scale.
        """
        loads = [sum(d.term_ids.nbytes + d.tfs.nbytes
                     for d in sd if d.live) for sd in self._shard_docs]
        slots = [len(sd) for sd in self._shard_docs]
        per_entries: list[list[DocEntry]] = [[] for _ in range(self.D)]
        for e in pending:
            s = int(np.argmin(loads))
            per_entries[s].append(e)
            loads[s] += e.term_ids.nbytes + e.tfs.nbytes
            slots[s] += 1
            if slots[s] > arrays.doc_cap:
                raise ValueError("docs-shard over doc capacity; re-shard")
        per_docs = [[dict(zip(e.term_ids.tolist(),
                              e.tfs.astype(np.float64).tolist()))
                     for e in es] for es in per_entries]
        per_lens = [
            list(self.model.transform_doc_len(
                np.asarray([e.length for e in es], np.float32))
                .astype(np.float32)) if es else []
            for es in per_entries]
        per_raw = [[e.length for e in es] for es in per_entries]
        max_entries = max((sum(e.term_ids.shape[0] for e in es)
                           for es in per_entries), default=0)
        C = next_capacity(max(-(-max_entries // self.T), 1), 64)
        batch = build_ingest_batch(self.mesh, arrays, per_docs, per_lens, C,
                                   raw_lengths_per_shard=per_raw)
        if self._ingest_fn is None:
            self._ingest_fn = make_sharded_ingest(self.mesh)
        arrays = self._ingest_fn(arrays, *batch)
        for s, es in enumerate(per_entries):
            for e in es:
                self._placed[e.name] = (s, len(self._shard_docs[s]))
                self._shard_docs[s].append(e)
        self.appends += 1
        global_metrics.inc("mesh_appends")
        return arrays


from tfidf_tpu.engine.searcher import QueryVectorizerMixin


class MeshSearcher(QueryVectorizerMixin):
    """Query execution against MeshSnapshots — the distributed forward
    pass. Mirrors :class:`~tfidf_tpu.engine.searcher.Searcher`'s interface
    so Engine/cluster code is layout-agnostic. Subclasses (the ELL mesh
    layout) override only the hooks — :meth:`_dispatch_chunk`,
    :meth:`_finish_chunk`, :meth:`_search_unbounded`,
    :meth:`_on_snapshot` — the chunking and hit-assembly loop lives in
    one place."""

    def __init__(self, index: MeshIndex, analyzer, vocab,
                 model: ScoringModel,
                 *, query_batch: int = 32, max_query_terms: int = 32,
                 top_k: int = 10, result_order: str = "score",
                 global_idf: bool = True,
                 kernel_a_build: str = "v4",
                 pipeline_depth: int = 2,
                 pipeline_mode: str = "auto") -> None:
        self.index = index
        self.analyzer = analyzer
        self.vocab = vocab
        self.model = model
        self.query_batch = query_batch
        self.max_query_terms = max_query_terms
        self.top_k = top_k
        self.result_order = result_order
        self.pipeline_depth = max(1, pipeline_depth)
        # "auto" | "executor" | "inline" — see QueryVectorizerMixin
        self.pipeline_mode = pipeline_mode
        # A-build variant for the fused kernel (ELL layout only; the
        # COO scatter step never touches it). Validated at
        # construction so a config typo fails before any query.
        from tfidf_tpu.ops.ell import check_a_build
        self.kernel_a_build = check_a_build(kernel_a_build)
        # global_idf=False reproduces the reference's per-worker statistics
        # (each Lucene shard scores against local df/N, Worker.java:222-241)
        self.global_idf = global_idf
        self._search_fns: dict[int, object] = {}
        self._scores_fn = None

    def _batch_cap(self, n: int) -> int:
        return min(self.query_batch, next_capacity(max(n, 1), 1))

    def _model_kwargs(self) -> dict:
        kw = dict(self.model.score_kwargs())
        kw.pop("model", None)
        return kw

    def _get_search_fn(self, k: int):
        fn = self._search_fns.get(k)
        if fn is None:
            fn = make_sharded_search(
                self.index.mesh, k=k,
                model=self.model.score_kwargs()["model"],
                global_idf=self.global_idf, packed=True,
                **self._model_kwargs())
            self._search_fns[k] = fn
        return fn

    def _get_scores_fn(self):
        if self._scores_fn is None:
            self._scores_fn = make_sharded_scores(
                self.index.mesh,
                model=self.model.score_kwargs()["model"],
                global_idf=self.global_idf, **self._model_kwargs())
        return self._scores_fn

    def search(self, queries: list[str], k: int | None = None,
               *, unbounded: bool = False):
        """Chunks are pipelined ``pipeline_depth`` deep, as in
        :meth:`tfidf_tpu.engine.searcher.Searcher.search`: later chunks'
        shard_map programs are dispatched before earlier chunks' packed
        top-k buffers are fetched, hiding the device->host RTT (which
        dominates device compute on small corpora)."""
        snap = self.index.snapshot
        self._on_snapshot(snap)
        if snap is None or snap.total_live == 0 or not queries:
            return [[] for _ in queries]
        if unbounded:
            return self._search_unbounded(snap, queries, k)
        k = self.top_k if k is None else k
        cap = self._batch_cap(len(queries))

        def dispatch(chunk):
            qb, _widest = self._vectorize(chunk,
                                          self._batch_cap(len(chunk)))
            return (chunk,) + self._dispatch_chunk(snap, qb, k)

        from tfidf_tpu.ops.topk import fetch_packed

        out = self._run_pipelined(
            (queries[lo:lo + cap]
             for lo in range(0, len(queries), cap)),
            dispatch,
            lambda chunk, packed, kk: (chunk, fetch_packed(packed), kk),
            lambda chunk, arr, kk: self._finish_chunk(snap, chunk, arr,
                                                      kk))
        global_metrics.inc("queries_served", len(queries))
        return out

    def _on_snapshot(self, snap) -> None:
        """Layout hook: called with the snapshot each search (lets
        subclasses drop per-snapshot caches when the version moves)."""

    def _dispatch_chunk(self, snap, qb, k: int):
        """Layout hook: launch one chunk's packed top-k (not fetched)."""
        kk = min(k, snap.arrays.doc_cap)
        return self._get_search_fn(kk)(snap.arrays, qb), kk

    def _finish_chunk(self, snap, chunk, packed, kk: int):
        # packed already crossed device->host in the fetch stage; this
        # runs on the caller's thread (views + hit assembly only)
        from tfidf_tpu.ops.topk import unpack_topk
        vals, gids = unpack_topk(packed)
        return self._assemble_hits(snap, chunk, vals, gids, kk)

    def _search_unbounded(self, snap, queries, k):
        """Layout hook: the reference's unbounded (parity) results."""
        out = []
        cap = self._batch_cap(len(queries))
        for lo in range(0, len(queries), cap):
            chunk = queries[lo:lo + cap]
            qb, _widest = self._vectorize(chunk,
                                          self._batch_cap(len(chunk)))
            vals, gids, kk = self._rank_all(snap, qb)
            out.extend(self._assemble_hits(snap, chunk, vals, gids, kk))
        global_metrics.inc("queries_served", len(queries))
        return out

    def _assemble_hits(self, snap, chunk, vals, gids, kk):
        from tfidf_tpu.engine.searcher import SearchHit
        results = []
        for i in range(len(chunk)):
            hits = []
            for v, g in zip(vals[i, :kk], gids[i, :kk]):
                if not (np.isfinite(v) and v > 0.0):
                    continue
                name = snap.name_of(int(g))
                if name is not None:
                    hits.append(SearchHit(name, float(v)))
            if self.result_order == "name":
                hits.sort(key=lambda h: h.name)
            results.append(hits)
        return results

    def _rank_all(self, snap: MeshSnapshot, qb):
        """Parity mode: full per-shard score matrices ranked on the host
        (the reference's unbounded Integer.MAX_VALUE results,
        ``Worker.java:230``). O(corpus) per query by definition."""
        scores = np.asarray(self._get_scores_fn()(snap.arrays, qb))
        D, B, doc_cap = scores.shape
        flat = scores.transpose(1, 0, 2).reshape(B, D * doc_cap)
        order = np.argsort(-flat, axis=1, kind="stable")
        vals = np.take_along_axis(flat, order, axis=1)
        return vals, order.astype(np.int64), D * doc_cap

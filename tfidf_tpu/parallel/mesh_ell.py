"""Blocked-ELL base layout for the mesh — the distributed fast path.

The COO ``shard_map`` step (:mod:`tfidf_tpu.parallel.sharded`) scores via
chunked ``segment_sum`` — a scatter, measured ~5x slower than the
single-device blocked-ELL path at equal scale. This module gives the mesh
the same layout the single-device engine uses (``ops/ell.py``), organized
for SPMD:

* per docs-shard, live documents are laid out as blocked ELL with a
  FIXED set of width buckets (8..width_cap) whose row capacities are
  padded to the max across shards — every device slice has identical
  static shapes, as ``shard_map`` requires;
* the ``terms`` axis shards each block's WIDTH columns: one document row
  keeps its entries split across terms-devices, partial scores
  ``psum``-reduce exactly like the COO path (entries are disjoint across
  slices; scores and df are additive);
* per-entry IMPACTS are (re)computed at every commit from the
  then-current global statistics (df summed over live host postings, N,
  avgdl) — appends between re-shards land in the COO *delta*
  (:class:`~tfidf_tpu.parallel.sharded.ShardedArrays`) and the next
  commit refreshes base impacts, so IDF never goes stale (the same
  current-stats contract as streaming segments / Lucene
  collectionStatistics);
* scoring uses the same compare/MXU Pallas kernel as the single-device
  path (``score_block_pallas``) inside ``shard_map`` — per-device
  kernels compose with collectives.

The ELL row order per shard is width-sorted, i.e. a PERMUTATION of the
shard's insertion-local ids; ``perm[s]`` maps ELL row -> insertion-local
id so the searcher can translate top-k ids back to names.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tfidf_tpu.parallel._compat import shard_map as _shard_map

from tfidf_tpu.ops.csr import next_capacity
from tfidf_tpu.ops.ell import (_pallas_eligible, _score_block,
                               score_block_pallas, _rearrange_to_real)
from tfidf_tpu.ops.scoring import (QueryBatch, _compile_queries,
                                   bm25_weights, score_coo_compiled,
                                   tfidf_weights)
from tfidf_tpu.ops.topk import exact_topk, merge_topk, pack_topk
from tfidf_tpu.utils.metrics import global_metrics

# fixed width buckets so every shard shares one block structure; every
# width is a multiple of 8 so the terms axis (up to 8-way) can shard the
# width columns evenly. The 1.5x intermediate steps cut pad entries
# ~13% vs pure powers of two (see ops/ell.py ELL_WIDTH_LADDER).
ELL_WIDTHS = (256, 192, 128, 96, 64, 48, 32, 24, 16, 8)


@dataclass
class MeshEllArrays:
    """Device-resident ELL base for the whole mesh.

    Per width bucket b: ``tf[b] [D, rows_cap_b, W_b]`` etc., sharded
    ``P("docs", None, "terms")``. ``doc_cap`` is the per-shard ELL doc
    space (block rows concatenated); ``live`` masks tombstones in that
    space.
    """

    tf: tuple            # per bucket f32 [D, rows_cap_b, W_b]
    term: tuple          # per bucket i32 [D, rows_cap_b, W_b]
    impact: tuple        # per bucket f32 [D, rows_cap_b, W_b]
    dl: tuple            # per bucket f32 [D, rows_cap_b]
    block_live: jax.Array  # i32 [D, n_buckets] live rows per block
    live: jax.Array      # f32 [D, doc_cap] in ELL row space
    # residual COO (over-wide docs), split over terms like the delta
    res_tf: jax.Array    # f32 [D, T, res_cap]
    res_term: jax.Array  # i32 [D, T, res_cap]
    res_doc: jax.Array   # i32 [D, T, res_cap] (ELL row ids)
    res_dl: jax.Array    # f32 [D, doc_cap] (model-transformed lengths)
    doc_cap: int

    @property
    def n_buckets(self) -> int:
        return len(self.tf)


jax.tree_util.register_dataclass(
    MeshEllArrays,
    data_fields=["tf", "term", "impact", "dl", "block_live", "live",
                 "res_tf", "res_term", "res_doc", "res_dl"],
    meta_fields=["doc_cap"],
)


def build_mesh_ell(entries_per_shard: list[list],   # list[DocEntry]/shard
                   mesh: Mesh,
                   transform_len,                   # model.transform_doc_len
                   *,
                   width_cap: int = 256,
                   min_rows: int = 256,
                   min_res_cap: int = 1 << 10
                   ) -> tuple[MeshEllArrays, list[np.ndarray]]:
    """Host-side build: per-shard blocked ELL with uniform buckets.

    Returns ``(arrays, perm)`` where ``perm[s][ell_row] = insertion-local
    id`` in shard s (for name lookup). Impacts are left zero — call
    :func:`make_impact_refresh` after placing the arrays.
    """
    D = mesh.shape["docs"]
    T = mesh.shape["terms"]
    widths = [w for w in ELL_WIDTHS if w <= width_cap]
    assert T <= min(widths), "terms axis cannot exceed the narrowest bucket"

    # per shard: sort rows by distinct-count desc, assign to buckets
    per_shard = []
    doc_caps = []
    rows_need = np.zeros((D, len(widths)), np.int64)
    res_need = np.zeros(D, np.int64)
    for s in range(D):
        entries = entries_per_shard[s]
        order = np.argsort([-e.term_ids.shape[0] for e in entries],
                           kind="stable")
        entries = [entries[i] for i in order]
        per_shard.append((entries, order))
        doc_caps.append(len(entries))
        for e in entries:
            k = e.term_ids.shape[0]
            b = _bucket_of(k, widths)
            rows_need[s, b] += 1
            if k > widths[b]:
                # spill size must use the BUCKET width (the widest rung
                # <= width_cap), not width_cap itself — for non-rung
                # caps the estimate would undercount the residual
                res_need[s] += k - widths[b]
    doc_cap = next_capacity(max(max(doc_caps, default=1), 1), min_rows)
    rows_cap = [next_capacity(int(rows_need[:, b].max()) or 1, min_rows)
                for b in range(len(widths))]
    res_cap = next_capacity(int(res_need.max()) or 1, min_res_cap)
    res_chunk = -(-res_cap // T)

    g_tf = [np.zeros((D, rows_cap[b], widths[b]), np.float32)
            for b in range(len(widths))]
    g_term = [np.zeros((D, rows_cap[b], widths[b]), np.int32)
              for b in range(len(widths))]
    g_dl = [np.zeros((D, rows_cap[b]), np.float32)
            for b in range(len(widths))]
    g_bl = np.zeros((D, len(widths)), np.int32)
    g_live = np.zeros((D, doc_cap), np.float32)
    g_res_tf = np.zeros((D, T, res_chunk), np.float32)
    g_res_term = np.zeros((D, T, res_chunk), np.int32)
    g_res_doc = np.full((D, T, res_chunk), doc_cap - 1, np.int32)
    g_res_dl = np.zeros((D, doc_cap), np.float32)
    perms = []
    for s in range(D):
        entries, order = per_shard[s]
        perms.append(order.astype(np.int64))
        cursors = np.zeros(len(widths), np.int64)
        res_rows, res_terms, res_tfs = [], [], []
        ell_row = 0
        raw = np.asarray([e.length for e in entries], np.float32)
        kdl = transform_len(raw).astype(np.float32) if len(entries) \
            else raw
        for i, e in enumerate(entries):
            k = e.term_ids.shape[0]
            b = _bucket_of(k, widths)
            r = int(cursors[b])
            cursors[b] += 1
            take = min(k, widths[b])
            g_tf[b][s, r, :take] = e.tfs[:take]
            g_term[b][s, r, :take] = e.term_ids[:take]
            g_dl[b][s, r] = kdl[i]
            if k > widths[b]:     # only the widest bucket can spill
                res_rows.extend([ell_row] * (k - take))
                res_terms.extend(e.term_ids[take:].tolist())
                res_tfs.extend(e.tfs[take:].tolist())
            g_live[s, ell_row] = 1.0
            g_res_dl[s, ell_row] = kdl[i]
            ell_row += 1
        g_bl[s] = cursors
        n_res = len(res_rows)
        step = -(-n_res // T) if n_res else 0
        for t in range(T):
            lo, hi = min(t * step, n_res), min((t + 1) * step, n_res)
            n = hi - lo
            if n:
                g_res_tf[s, t, :n] = res_tfs[lo:hi]
                g_res_term[s, t, :n] = res_terms[lo:hi]
                g_res_doc[s, t, :n] = res_rows[lo:hi]

    # device-residency accounting (ISSUE 18): the mesh base is always
    # fully resident (no cold tier on the mesh path), so publish its
    # HBM footprint on the same gauge family the tiered single-device
    # engine reports under — capacity dashboards read one bytes number
    # per node regardless of layout. tf counts twice: the impact plane
    # is a same-shape f32 copy.
    dev_bytes = (sum(a.nbytes for a in g_tf) * 2
                 + sum(a.nbytes for a in g_term)
                 + sum(a.nbytes for a in g_dl)
                 + g_bl.nbytes + g_live.nbytes + g_res_tf.nbytes
                 + g_res_term.nbytes + g_res_doc.nbytes
                 + g_res_dl.nbytes)
    global_metrics.set_gauge("mesh_ell_device_bytes", float(dev_bytes))

    def put(x, spec):
        return jax.device_put(x, NamedSharding(mesh, spec))

    # width columns shard over "terms": entries of one row split across
    # terms-devices; contributions are additive, like the COO split
    arrays = MeshEllArrays(
        tf=tuple(put(a, P("docs", None, "terms")) for a in g_tf),
        term=tuple(put(a, P("docs", None, "terms")) for a in g_term),
        impact=tuple(put(np.zeros_like(a), P("docs", None, "terms"))
                     for a in g_tf),
        dl=tuple(put(a, P("docs", None)) for a in g_dl),
        block_live=put(g_bl, P("docs", None)),
        live=put(g_live, P("docs", None)),
        res_tf=put(g_res_tf, P("docs", "terms", None)),
        res_term=put(g_res_term, P("docs", "terms", None)),
        res_doc=put(g_res_doc, P("docs", "terms", None)),
        res_dl=put(g_res_dl, P("docs", None)),
        doc_cap=doc_cap,
    )
    return arrays, perms


def _bucket_of(k: int, widths: list[int]) -> int:
    """Smallest bucket with width >= k; over-wide rows use bucket 0 and
    spill the excess into the residual."""
    for b in range(len(widths) - 1, -1, -1):
        if k <= widths[b]:
            return b
    return 0


def make_impact_refresh(mesh: Mesh, *, model: str = "bm25",
                        k1: float = 1.2, b: float = 0.75):
    """Commit-time impact recompute from CURRENT global stats.

    ``refresh(arrays, df_g [vocab], n, avgdl) -> MeshEllArrays`` — df_g
    is replicated; each slice re-derives its impacts from its raw tf, so
    appends (which move df/N/avgdl) never leave stale IDF in the base.
    """

    def step(df_g, n_docs, avgdl, *flat):
        k = len(flat) // 3
        tfs, terms, dls = flat[:k], flat[k:2 * k], flat[2 * k:]
        out = []
        for tf, term, dl in zip(tfs, terms, dls):
            tf = tf.reshape(tf.shape[1:])            # [rows, Wt]
            term = term.reshape(term.shape[1:])
            dl = dl.reshape(dl.shape[-1])            # [rows]
            df_t = df_g[term]
            if model == "bm25":
                imp = bm25_weights(tf, df_t, dl[:, None], n_docs, avgdl,
                                   k1=k1, b=b)
            elif model == "tfidf":
                imp = tfidf_weights(tf, df_t, n_docs)
            else:
                raise ValueError(f"mesh ELL does not support {model!r}")
            out.append(imp[None])
        return tuple(out)

    def n_in(k):
        return ((P(None),) + (P(),) * 2
                + (P("docs", None, "terms"),) * k * 2
                + (P("docs", None),) * k)

    def refresh(arrays: MeshEllArrays, df_g, n_docs, avgdl):
        import dataclasses
        k = arrays.n_buckets
        sharded = _shard_map(
            step, mesh=mesh, in_specs=n_in(k),
            out_specs=(P("docs", None, "terms"),) * k,
            check_vma=False)
        impacts = sharded(df_g, n_docs, avgdl,
                          *arrays.tf, *arrays.term, *arrays.dl)
        return dataclasses.replace(arrays, impact=tuple(impacts))

    return jax.jit(refresh)


def make_mesh_ell_search(mesh: Mesh,
                         delta_chunk: int = 1 << 17,
                         *,
                         k: int,
                         model: str = "bm25",
                         k1: float = 1.2,
                         b: float = 0.75,
                         use_pallas: bool = True,
                         a_build: str = "v4",
                         packed: bool = False):
    """Distributed search over ELL base + COO delta.

    Returned callable:
        search(base: MeshEllArrays, delta: ShardedArrays, df_g, n, avgdl,
               q: QueryBatch) -> (top_vals [B,k], gids [B,k])

    ``gids`` encode shard * (doc_cap_ell + doc_cap_delta) + local, where
    local < doc_cap_ell is an ELL row and local >= doc_cap_ell is a
    delta slot. Global stats arrive precomputed (the engine refreshes
    them at commit), so the step needs no df psum.

    ``packed=True`` returns ONE i32 ``[B, 2k]`` array (values bitcast) so
    the caller fetches values and ids in a single device->host transfer
    — on high-latency links (remote-TPU tunnels) the second fetch costs
    a full RTT, which at k=10 dwarfs the payload.
    """

    def step(df_g, n_docs, avgdl, base_live, block_live,
             res_tf, res_term, res_doc, res_dl,
             d_tf, d_term, d_doc, d_len, d_n, d_live,
             q_uniq, q_n_uniq, q_slots, q_weights, *blocks):
        q = QueryBatch(q_uniq, q_n_uniq, q_slots, q_weights)
        nb = len(blocks) // 2
        impacts = [x.reshape(x.shape[1:]) for x in blocks[:nb]]
        terms = [x.reshape(x.shape[1:]) for x in blocks[nb:]]
        base_live = base_live.reshape(base_live.shape[-1])
        block_live = block_live.reshape(block_live.shape[-1])
        res_tf = res_tf.reshape(res_tf.shape[-1])
        res_term = res_term.reshape(res_term.shape[-1])
        res_doc = res_doc.reshape(res_doc.shape[-1])
        res_dl = res_dl.reshape(res_dl.shape[-1])
        d_tf = d_tf.reshape(d_tf.shape[-1])
        d_term = d_term.reshape(d_term.shape[-1])
        d_doc = d_doc.reshape(d_doc.shape[-1])
        d_len = d_len.reshape(d_len.shape[-1])
        d_n = d_n.reshape(())
        d_live = d_live.reshape(d_live.shape[-1])

        B = q.slots.shape[0]
        vocab_cap = df_g.shape[0]
        doc_cap_ell = base_live.shape[0]
        doc_cap_delta = d_live.shape[0]
        slot_of, qc_ext = _compile_queries(q, vocab_cap)
        qc_t = qc_ext.T
        u_cap = q.uniq.shape[0]

        # --- ELL base: same per-block scorers as single-device ---
        parts = []
        for i, (imp, term) in enumerate(zip(impacts, terms)):
            if use_pallas and _pallas_eligible(imp.shape[0], B, u_cap,
                                               a_build):
                parts.append(score_block_pallas(
                    imp, term, q.uniq, q.n_uniq, qc_ext, block_live[i],
                    a_build=a_build, vocab_cap=vocab_cap))
            else:
                parts.append(_score_block(imp, term, slot_of, qc_t, 2048))
        ell_scores = _rearrange_to_real(
            parts, [imp.shape[0] for imp in impacts], block_live,
            doc_cap_ell, B)
        ell_scores = ell_scores + score_coo_compiled(
            res_tf, res_term, res_doc, res_dl, df_g, slot_of, qc_ext,
            n_docs, avgdl, None, model=model, k1=k1, b=b,
            chunk=min(1 << 10, res_tf.shape[0]))
        ell_scores = jax.lax.psum(ell_scores, "terms")
        ell_scores = ell_scores * base_live[None, :]

        # --- COO delta (appends since the last re-shard) ---
        delta_scores = score_coo_compiled(
            d_tf, d_term, d_doc, d_len, df_g, slot_of, qc_ext,
            n_docs, avgdl, None, model=model, k1=k1, b=b,
            chunk=min(delta_chunk, d_tf.shape[0]))
        delta_scores = jax.lax.psum(delta_scores, "terms")
        delta_scores = delta_scores * d_live[None, :]

        scores = jnp.concatenate([ell_scores, delta_scores], axis=1)
        n_local = jnp.int32(doc_cap_ell) + d_n
        # mask via per-position liveness, not a row-count prefix: the
        # ELL space is permuted, so exact_topk's prefix mask is wrong —
        # dead positions already score 0 and top_k handles the rest
        vals, ids = exact_topk(scores, n_local, k=k)
        shard_idx = jax.lax.axis_index("docs").astype(jnp.int32)
        gids = (shard_idx * jnp.int32(doc_cap_ell + doc_cap_delta)
                + ids)
        all_vals = jax.lax.all_gather(vals, "docs")
        all_ids = jax.lax.all_gather(gids, "docs")
        return merge_topk(all_vals, all_ids)

    def in_specs(nb):
        return ((P(None), P(), P(),
                 P("docs", None), P("docs", None),
                 P("docs", "terms", None), P("docs", "terms", None),
                 P("docs", "terms", None), P("docs", None),
                 P("docs", "terms", None), P("docs", "terms", None),
                 P("docs", "terms", None), P("docs", None), P("docs"),
                 P("docs", None),
                 P(None), P(), P(None, None), P(None, None))
                + (P("docs", None, "terms"),) * nb * 2)

    @jax.jit
    def search(base: MeshEllArrays, delta, df_g, n_docs, avgdl,
               q: QueryBatch):
        nb = base.n_buckets
        sharded = _shard_map(
            step, mesh=mesh, in_specs=in_specs(nb),
            out_specs=(P(), P()), check_vma=False)
        vals, gids = sharded(
            df_g, n_docs, avgdl, base.live, base.block_live,
            base.res_tf, base.res_term, base.res_doc, base.res_dl,
            delta.tf, delta.term, delta.doc, delta.doc_len,
            delta.n_live, delta.live,
            jnp.asarray(q.uniq), jnp.asarray(q.n_uniq),
            jnp.asarray(q.slots), jnp.asarray(q.weights),
            *base.impact, *base.term)
        if packed:
            return pack_topk(vals, gids)
        return vals, gids

    return search


def with_ell_live(mesh: Mesh, arrays: MeshEllArrays,
                  live_host: np.ndarray) -> MeshEllArrays:
    """Tombstone update in ELL row space (host-rebuilt, like the delta's
    :func:`~tfidf_tpu.parallel.sharded.with_live_mask`)."""
    import dataclasses
    live = jax.device_put(live_host.astype(np.float32),
                          NamedSharding(mesh, P("docs", None)))
    return dataclasses.replace(arrays, live=live)

"""jax version compatibility for the parallel layer.

``jax.shard_map`` moved to the top-level namespace (and its ``check_rep``
kwarg became ``check_vma``) only in newer jax; on older versions
(< 0.4.38) it lives under ``jax.experimental.shard_map``. Resolve the
difference once, here, so every call site is version-agnostic.
"""

from __future__ import annotations

import jax

shard_map = getattr(jax, "shard_map", None)
if shard_map is None:   # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def shard_map(f, **kw):
        # the experimental API spells check_vma as check_rep
        kw["check_rep"] = kw.pop("check_vma", False)
        return _exp_shard_map(f, **kw)

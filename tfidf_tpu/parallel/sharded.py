"""Sharded scoring over the device mesh — the distributed forward step.

This subsumes the reference's entire scatter-gather data path
(``leader/Leader.java:39-92``: serial HTTP fan-out to every worker, JSON
score lists back, ``Map.merge`` sum at the leader) with one ``shard_map``
program over a ``("docs", "terms")`` mesh:

    scatter  -> the query batch is replicated to every device by sharding
    per-shard scoring -> local COO postings scored on-device
    global IDF        -> ``psum`` of per-shard document frequencies over the
                         whole mesh (the reference never globalizes IDF —
                         each Lucene worker scores against local stats; we
                         expose that behavior as parity mode and global IDF
                         as the default, SURVEY.md §7 Phase B)
    score reduce      -> ``psum`` of partial scores over the ``terms`` axis
    gather   -> per-docs-shard exact top-k, ``all_gather`` over ``docs``,
                associative re-top-k; every device ends with the answer

Collectives ride ICI inside one jitted program — there is no host round-trip
per worker, which is where the >=50x headroom over the Java system lives.

Host-side layout (``build_sharded_arrays``): documents are dealt
round-robin into ``D`` docs-shards (upload balancing is handled upstream by
the engine); each shard's row-sorted COO is split into ``T`` contiguous
chunks along nnz. Any disjoint partition of entries is correct because both
df and scores are additive over entries; contiguous chunking keeps the
partition balanced to within one entry.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tfidf_tpu.ops.csr import CooShard, next_capacity
from tfidf_tpu.ops.scoring import cosine_norms, score_coo_impl
from tfidf_tpu.ops.topk import exact_topk, merge_topk


@dataclass
class ShardedArrays:
    """Global (addressable-on-mesh) arrays for the whole corpus.

    Leading axes: D = docs shards, T = terms shards.
    """

    tf: jax.Array        # f32 [D, T, chunk_cap]
    term: jax.Array      # i32 [D, T, chunk_cap]
    doc: jax.Array       # i32 [D, T, chunk_cap]
    doc_len: jax.Array   # f32 [D, doc_cap]
    df: jax.Array        # f32 [D, T, vocab_cap] (per-shard partial df)
    n_live: jax.Array    # i32 [D] live docs per docs-shard
    doc_cap: int
    vocab_cap: int

    @property
    def shape_dt(self) -> tuple[int, int]:
        return self.tf.shape[0], self.tf.shape[1]


jax.tree_util.register_dataclass(
    ShardedArrays,
    data_fields=["tf", "term", "doc", "doc_len", "df", "n_live"],
    meta_fields=["doc_cap", "vocab_cap"],
)


def shard_documents(n_docs: int, n_shards: int) -> np.ndarray:
    """Round-robin placement: doc i -> shard i % D (balanced, deterministic).

    The engine's least-loaded placement (reference ``Leader.java:168-189``)
    applies at ingest; this is the static layout for mesh-resident scoring.
    """
    return np.arange(n_docs, dtype=np.int64) % n_shards


def build_sharded_arrays(shard: CooShard,
                         mesh: Mesh,
                         min_chunk_cap: int = 1 << 14) -> ShardedArrays:
    """Partition one host COO shard across a (docs, terms) mesh.

    Returns device arrays placed with NamedShardings so each mesh slice
    holds exactly its block.
    """
    D = mesh.shape["docs"]
    T = mesh.shape["terms"]
    nnz, n_docs = shard.nnz, shard.num_docs
    tf = np.asarray(shard.tf)[:nnz]
    term = np.asarray(shard.term)[:nnz]
    doc = np.asarray(shard.doc)[:nnz].astype(np.int64)
    doc_len_src = np.asarray(shard.doc_len)
    vocab_cap = shard.vocab_cap

    assign = shard_documents(n_docs, D)          # global doc -> docs shard
    local_id = np.zeros(n_docs, np.int64)
    counts = np.zeros(D, np.int64)
    for s in range(D):
        mask = assign == s
        local_id[mask] = np.arange(mask.sum())
        counts[s] = mask.sum()
    doc_cap = next_capacity(max(int(counts.max()) if D else 1, 1), 1024)

    entry_shard = assign[doc]                    # nnz -> docs shard
    chunk_caps = []
    per_shard = []
    for s in range(D):
        m = entry_shard == s
        k = int(m.sum())
        per_shard.append((tf[m], term[m], local_id[doc[m]].astype(np.int32)))
        chunk_caps.append(-(-k // T))            # ceil split over terms
    chunk_cap = next_capacity(max(max(chunk_caps, default=1), 1),
                              min_chunk_cap)

    g_tf = np.zeros((D, T, chunk_cap), np.float32)
    g_term = np.zeros((D, T, chunk_cap), np.int32)
    g_doc = np.zeros((D, T, chunk_cap), np.int32)
    g_len = np.zeros((D, doc_cap), np.float32)
    g_df = np.zeros((D, T, vocab_cap), np.float32)
    for s in range(D):
        stf, sterm, sdoc = per_shard[s]
        k = stf.shape[0]
        for t in range(T):
            lo = t * -(-k // T) if k else 0
            hi = min(k, (t + 1) * -(-k // T)) if k else 0
            n = max(hi - lo, 0)
            if n > 0:
                g_tf[s, t, :n] = stf[lo:hi]
                g_term[s, t, :n] = sterm[lo:hi]
                g_doc[s, t, :n] = sdoc[lo:hi]
                # df is additive over any disjoint entry partition, but must
                # count each (doc, term) pair once — COO entries are unique
                # pairs, so counting entries is exactly df.
                np.add.at(g_df[s, t], sterm[lo:hi], 1.0)
        live = assign == s
        g_len[s, :int(counts[s])] = doc_len_src[:n_docs][live]

    def put(x, spec):
        return jax.device_put(x, NamedSharding(mesh, spec))

    return ShardedArrays(
        tf=put(g_tf, P("docs", "terms", None)),
        term=put(g_term, P("docs", "terms", None)),
        doc=put(g_doc, P("docs", "terms", None)),
        doc_len=put(g_len, P("docs", None)),
        df=put(g_df, P("docs", "terms", None)),
        n_live=put(counts.astype(np.int32), P("docs")),
        doc_cap=doc_cap,
        vocab_cap=vocab_cap,
    )


def global_stats(arrays: ShardedArrays) -> tuple[jax.Array, jax.Array]:
    """(N, avgdl) over the whole mesh — host-visible scalars."""
    n = jnp.sum(arrays.n_live).astype(jnp.float32)
    total = jnp.sum(arrays.doc_len)
    return n, total / jnp.maximum(n, 1.0)


def make_sharded_search(mesh: Mesh,
                        *,
                        k: int,
                        model: str = "bm25",
                        k1: float = 1.2,
                        b: float = 0.75,
                        global_idf: bool = True,
                        chunk: int = 1 << 17):
    """Build the jitted distributed search step for a fixed mesh/model.

    Returned callable:
        step(arrays: ShardedArrays, q_terms [B,T_q], q_weights [B,T_q])
            -> (top_vals [B,k], top_global_ids [B,k])

    ``top_global_ids`` encode (docs_shard, local_id) as shard * doc_cap + id;
    the engine maps them back to document names.

    ``global_idf=False`` reproduces the reference's per-worker statistics
    (each Lucene shard scores against local df/N — ``Worker.java:222-241``)
    for parity testing.
    """

    def step(tf, term, doc, doc_len, df, n_live, q_terms, q_weights):
        tf = tf.reshape(tf.shape[-1])
        term = term.reshape(term.shape[-1])
        doc = doc.reshape(doc.shape[-1])
        doc_len = doc_len.reshape(doc_len.shape[-1])
        df_local = df.reshape(df.shape[-1])
        n_local = n_live.reshape(())

        doc_cap = doc_len.shape[0]

        if global_idf:
            # THE collective the north star names: global document frequency
            # via psum over the whole mesh (entries are disjoint across both
            # axes, so summing both is exact).
            df_eff = jax.lax.psum(df_local, ("docs", "terms"))
            n_eff = jax.lax.psum(n_local.astype(jnp.float32), "docs")
            total_len = jax.lax.psum(jnp.sum(doc_len), "docs")
            avgdl = total_len / jnp.maximum(n_eff, 1.0)
        else:
            # Parity mode: per-docs-shard stats, as each Java worker sees.
            df_eff = jax.lax.psum(df_local, "terms")
            n_eff = n_local.astype(jnp.float32)
            avgdl = jnp.sum(doc_len) / jnp.maximum(n_eff, 1.0)

        doc_norms = None
        if model == "tfidf_cosine":
            # Norms depend on (global) df, so they are computed in-step:
            # per-entry squared weights segment-summed locally, then reduced
            # over the terms axis (a document's entries span terms shards).
            sq = cosine_norms(tf, term, doc, df_eff, n_eff, doc_cap) ** 2
            doc_norms = jnp.sqrt(jax.lax.psum(sq, "terms"))

        partial = score_coo_impl(
            tf, term, doc, doc_len, df_eff, q_terms, q_weights,
            n_eff, avgdl, doc_norms, model=model, k1=k1, b=b, chunk=chunk)

        scores = jax.lax.psum(partial, "terms")        # [B, doc_cap]
        vals, ids = exact_topk(scores, n_local, k=k)
        shard_idx = jax.lax.axis_index("docs").astype(jnp.int32)
        gids = shard_idx * jnp.int32(doc_cap) + ids

        all_vals = jax.lax.all_gather(vals, "docs")    # [D, B, k]
        all_ids = jax.lax.all_gather(gids, "docs")
        top_vals, top_ids = merge_topk(all_vals, all_ids)
        return top_vals, top_ids

    sharded = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(P("docs", "terms", None), P("docs", "terms", None),
                  P("docs", "terms", None), P("docs", None),
                  P("docs", "terms", None), P("docs"),
                  P(None, None), P(None, None)),
        out_specs=(P(), P()),
        check_vma=False,
    )

    @jax.jit
    def search(arrays: ShardedArrays, q_terms, q_weights):
        return sharded(arrays.tf, arrays.term, arrays.doc, arrays.doc_len,
                       arrays.df, arrays.n_live, q_terms, q_weights)

    return search

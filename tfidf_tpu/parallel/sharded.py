"""Sharded scoring over the device mesh — the distributed forward step.

This subsumes the reference's entire scatter-gather data path
(``leader/Leader.java:39-92``: serial HTTP fan-out to every worker, JSON
score lists back, ``Map.merge`` sum at the leader) with one ``shard_map``
program over a ``("docs", "terms")`` mesh:

    scatter  -> the query batch is replicated to every device by sharding
    per-shard scoring -> local COO postings scored on-device
    global IDF        -> ``psum`` of per-shard document frequencies over the
                         whole mesh (the reference never globalizes IDF —
                         each Lucene worker scores against local stats; we
                         expose that behavior as parity mode and global IDF
                         as the default, SURVEY.md §7 Phase B)
    score reduce      -> ``psum`` of partial scores over the ``terms`` axis
    gather   -> per-docs-shard exact top-k, ``all_gather`` over ``docs``,
                associative re-top-k; every device ends with the answer

Collectives ride ICI inside one jitted program — there is no host round-trip
per worker, which is where the >=50x headroom over the Java system lives.

Host-side layout (``build_sharded_arrays``): documents are dealt
round-robin into ``D`` docs-shards (upload balancing is handled upstream by
the engine); each shard's row-sorted COO is split into ``T`` contiguous
chunks along nnz. Any disjoint partition of entries is correct because both
df and scores are additive over entries; contiguous chunking keeps the
partition balanced to within one entry.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tfidf_tpu.parallel._compat import shard_map as _shard_map

from tfidf_tpu.ops.csr import CooShard, next_capacity
from tfidf_tpu.ops.scoring import (QueryBatch, cosine_norms,
                                   score_coo_impl)
from tfidf_tpu.ops.topk import exact_topk, merge_topk, pack_topk


@dataclass
class ShardedArrays:
    """Global (addressable-on-mesh) arrays for the whole corpus.

    Leading axes: D = docs shards, T = terms shards.
    """

    tf: jax.Array        # f32 [D, T, chunk_cap]
    term: jax.Array      # i32 [D, T, chunk_cap]
    doc: jax.Array       # i32 [D, T, chunk_cap]
    doc_len: jax.Array   # f32 [D, doc_cap]
    df: jax.Array        # f32 [D, T, vocab_cap] (per-shard partial df)
    n_live: jax.Array    # i32 [D] occupied doc slots (append cursor)
    nnz_used: jax.Array  # i32 [D, T] entries in use per block (append cursor)
    # Tombstone mask, Lucene's deleted-docs bitmap at mesh scale: deleted
    # docs keep their postings (and stay in df/avgdl until a re-shard
    # compaction, like Lucene until merge) but score 0.
    live: jax.Array      # f32 [D, doc_cap] — 1=live, 0=tombstone/pad
    # Sum of RAW (pre-norm-quantization) lengths per shard: avgdl must be
    # computed from exact lengths (Lucene: sumTotalTermFreq / docCount)
    # even when doc_len holds SmallFloat-quantized values (parity mode).
    len_sum: jax.Array   # f32 [D]
    doc_cap: int
    vocab_cap: int



jax.tree_util.register_dataclass(
    ShardedArrays,
    data_fields=["tf", "term", "doc", "doc_len", "df", "n_live", "nnz_used",
                 "live", "len_sum"],
    meta_fields=["doc_cap", "vocab_cap"],
)


def host_value(x) -> np.ndarray:
    """Fetch a (small) device array to host, multi-process-safe.

    Single-controller: a plain fetch. Under ``jax.distributed`` a
    sharded array spans non-addressable devices, so the fetch is a
    ``process_allgather`` collective — EVERY process must reach this
    call in the same program order (the SPMD discipline mesh commits
    already require: all processes ingest and commit identically)."""
    if jax.process_count() == 1:
        return np.asarray(x)
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


def _split_ranges(k: int, t_parts: int) -> list[tuple[int, int]]:
    """Contiguous ceil-split of k entries over t_parts terms blocks — the
    single source of truth for the entry partition (build and ingest must
    agree or append cursors desync from the layout)."""
    step = -(-k // t_parts) if k else 0
    return [(min(t * step, k), min((t + 1) * step, k))
            for t in range(t_parts)]


def shard_documents(n_docs: int, n_shards: int) -> np.ndarray:
    """Round-robin placement: doc i -> shard i % D (balanced, deterministic).

    The engine's least-loaded placement (reference ``Leader.java:168-189``)
    applies at ingest; this is the static layout for mesh-resident scoring.
    """
    return np.arange(n_docs, dtype=np.int64) % n_shards


def build_sharded_arrays(shard: CooShard,
                         mesh: Mesh,
                         min_chunk_cap: int = 1 << 14,
                         min_doc_cap: int = 1024,
                         headroom: float = 0.25,
                         raw_doc_len: np.ndarray | None = None
                         ) -> ShardedArrays:
    """Partition one host COO shard across a (docs, terms) mesh.

    Returns device arrays placed with NamedShardings so each mesh slice
    holds exactly its block. ``headroom`` over-allocates the capacity
    buckets so subsequent on-device appends have a free tail even when the
    exact need lands on a power-of-two boundary (otherwise a rebuild right
    at a boundary would overflow on the very next commit).

    ``raw_doc_len`` (defaults to ``shard.doc_len``): exact pre-quantization
    lengths, used only for the per-shard avgdl sums — pass it when
    ``shard.doc_len`` holds norm-transformed values (Lucene parity).
    """
    D = mesh.shape["docs"]
    T = mesh.shape["terms"]
    nnz, n_docs = shard.nnz, shard.num_docs
    tf = np.asarray(shard.tf)[:nnz]
    term = np.asarray(shard.term)[:nnz]
    doc = np.asarray(shard.doc)[:nnz].astype(np.int64)
    doc_len_src = np.asarray(shard.doc_len)
    vocab_cap = shard.vocab_cap

    assign = shard_documents(n_docs, D)          # global doc -> docs shard
    local_id = np.zeros(n_docs, np.int64)
    counts = np.zeros(D, np.int64)
    for s in range(D):
        mask = assign == s
        local_id[mask] = np.arange(mask.sum())
        counts[s] = mask.sum()
    grow = 1.0 + max(headroom, 0.0)
    doc_cap = next_capacity(
        int(max(int(counts.max()) if D else 1, 1) * grow) + 1, min_doc_cap)

    entry_shard = assign[doc]                    # nnz -> docs shard
    chunk_caps = []
    per_shard = []
    for s in range(D):
        m = entry_shard == s
        k = int(m.sum())
        per_shard.append((tf[m], term[m], local_id[doc[m]].astype(np.int32)))
        chunk_caps.append(-(-k // T))            # ceil split over terms
    chunk_cap = next_capacity(
        int(max(max(chunk_caps, default=1), 1) * grow) + 1, min_chunk_cap)

    g_tf = np.zeros((D, T, chunk_cap), np.float32)
    g_term = np.zeros((D, T, chunk_cap), np.int32)
    # sorted-padding: free entries point at the last row (zero contribution)
    g_doc = np.full((D, T, chunk_cap), doc_cap - 1, np.int32)
    g_len = np.zeros((D, doc_cap), np.float32)
    g_df = np.zeros((D, T, vocab_cap), np.float32)
    g_used = np.zeros((D, T), np.int32)
    for s in range(D):
        stf, sterm, sdoc = per_shard[s]
        for t, (lo, hi) in enumerate(_split_ranges(stf.shape[0], T)):
            n = hi - lo
            g_used[s, t] = n
            if n > 0:
                g_tf[s, t, :n] = stf[lo:hi]
                g_term[s, t, :n] = sterm[lo:hi]
                g_doc[s, t, :n] = sdoc[lo:hi]
                # df is additive over any disjoint entry partition, but must
                # count each (doc, term) pair once — COO entries are unique
                # pairs, so counting entries is exactly df.
                np.add.at(g_df[s, t], sterm[lo:hi], 1.0)
        live = assign == s
        g_len[s, :int(counts[s])] = doc_len_src[:n_docs][live]

    def put(x, spec):
        return jax.device_put(x, NamedSharding(mesh, spec))

    g_live = (np.arange(doc_cap)[None, :]
              < counts[:, None]).astype(np.float32)
    raw = (np.asarray(raw_doc_len) if raw_doc_len is not None
           else doc_len_src)[:n_docs]
    g_len_sum = np.zeros(D, np.float32)
    for s in range(D):
        g_len_sum[s] = float(raw[assign == s].sum())
    return ShardedArrays(
        tf=put(g_tf, P("docs", "terms", None)),
        term=put(g_term, P("docs", "terms", None)),
        doc=put(g_doc, P("docs", "terms", None)),
        doc_len=put(g_len, P("docs", None)),
        df=put(g_df, P("docs", "terms", None)),
        n_live=put(counts.astype(np.int32), P("docs")),
        nnz_used=put(g_used, P("docs", "terms")),
        live=put(g_live, P("docs", None)),
        len_sum=put(g_len_sum, P("docs")),
        doc_cap=doc_cap,
        vocab_cap=vocab_cap,
    )


def global_stats(arrays: ShardedArrays) -> tuple[jax.Array, jax.Array]:
    """(N, avgdl) over the whole mesh — host-visible scalars."""
    n = jnp.sum(arrays.n_live).astype(jnp.float32)
    total = jnp.sum(arrays.doc_len)
    return n, total / jnp.maximum(n, 1.0)


def make_sharded_search(mesh: Mesh,
                        *,
                        k: int,
                        model: str = "bm25",
                        k1: float = 1.2,
                        b: float = 0.75,
                        global_idf: bool = True,
                        chunk: int = 1 << 17,
                        packed: bool = False):
    """Build the jitted distributed search step for a fixed mesh/model.

    Returned callable:
        step(arrays: ShardedArrays, q_terms [B,T_q], q_weights [B,T_q])
            -> (top_vals [B,k], top_global_ids [B,k])

    ``top_global_ids`` encode (docs_shard, local_id) as shard * doc_cap + id;
    the engine maps them back to document names.

    ``global_idf=False`` reproduces the reference's per-worker statistics
    (each Lucene shard scores against local df/N — ``Worker.java:222-241``)
    for parity testing.
    """

    def step(tf, term, doc, doc_len, df, n_live, live, len_sum,
             q_uniq, q_n_uniq, q_slots, q_weights):
        q = QueryBatch(q_uniq, q_n_uniq, q_slots, q_weights)
        tf = tf.reshape(tf.shape[-1])
        term = term.reshape(term.shape[-1])
        doc = doc.reshape(doc.shape[-1])
        doc_len = doc_len.reshape(doc_len.shape[-1])
        df_local = df.reshape(df.shape[-1])
        n_local = n_live.reshape(())
        live = live.reshape(live.shape[-1])
        len_local = len_sum.reshape(())

        doc_cap = doc_len.shape[0]

        if global_idf:
            # THE collective the north star names: global document frequency
            # via psum over the whole mesh (entries are disjoint across both
            # axes, so summing both is exact).
            df_eff = jax.lax.psum(df_local, ("docs", "terms"))
            n_eff = jax.lax.psum(n_local.astype(jnp.float32), "docs")
            total_len = jax.lax.psum(len_local, "docs")
            avgdl = total_len / jnp.maximum(n_eff, 1.0)
        else:
            # Parity mode: per-docs-shard stats, as each Java worker sees.
            df_eff = jax.lax.psum(df_local, "terms")
            n_eff = n_local.astype(jnp.float32)
            avgdl = len_local / jnp.maximum(n_eff, 1.0)

        doc_norms = None
        if model == "tfidf_cosine":
            # Norms depend on (global) df, so they are computed in-step:
            # per-entry squared weights segment-summed locally, then reduced
            # over the terms axis (a document's entries span terms shards).
            sq = cosine_norms(tf, term, doc, df_eff, n_eff, doc_cap) ** 2
            doc_norms = jnp.sqrt(jax.lax.psum(sq, "terms"))

        partial = score_coo_impl(
            tf, term, doc, doc_len, df_eff, q,
            n_eff, avgdl, doc_norms, model=model, k1=k1, b=b, chunk=chunk)

        scores = jax.lax.psum(partial, "terms")        # [B, doc_cap]
        scores = scores * live[None, :]                # zero tombstones
        vals, ids = exact_topk(scores, n_local, k=k)
        shard_idx = jax.lax.axis_index("docs").astype(jnp.int32)
        gids = shard_idx * jnp.int32(doc_cap) + ids

        all_vals = jax.lax.all_gather(vals, "docs")    # [D, B, k]
        all_ids = jax.lax.all_gather(gids, "docs")
        top_vals, top_ids = merge_topk(all_vals, all_ids)
        return top_vals, top_ids

    sharded = _shard_map(
        step,
        mesh=mesh,
        in_specs=(P("docs", "terms", None), P("docs", "terms", None),
                  P("docs", "terms", None), P("docs", None),
                  P("docs", "terms", None), P("docs"), P("docs", None),
                  P("docs"),
                  P(None), P(), P(None, None), P(None, None)),
        out_specs=(P(), P()),
        check_vma=False,
    )

    @jax.jit
    def search(arrays: ShardedArrays, q: QueryBatch):
        vals, gids = sharded(
            arrays.tf, arrays.term, arrays.doc, arrays.doc_len,
            arrays.df, arrays.n_live, arrays.live,
            arrays.len_sum,
            jnp.asarray(q.uniq), jnp.asarray(q.n_uniq),
            jnp.asarray(q.slots), jnp.asarray(q.weights))
        if packed:
            # one [B, 2k] i32 buffer: bitcast values + ids fetched in a
            # single device->host transfer (the second fetch costs a full
            # RTT on tunneled links)
            return pack_topk(vals, gids)
        return vals, gids

    return search


def make_sharded_scores(mesh: Mesh,
                        *,
                        model: str = "bm25",
                        k1: float = 1.2,
                        b: float = 0.75,
                        global_idf: bool = True,
                        chunk: int = 1 << 17):
    """Full per-shard score matrices — the parity-mode (unbounded) path.

    Returned callable:
        step(arrays, q...) -> scores [D, B, doc_cap], sharded over docs.

    The host ranks the full matrix (the reference's ``Integer.MAX_VALUE``
    behavior, ``Worker.java:230``); O(corpus) per query by definition, so
    this never rides the serving fast path.
    """

    def step(tf, term, doc, doc_len, df, n_live, live, len_sum,
             q_uniq, q_n_uniq, q_slots, q_weights):
        q = QueryBatch(q_uniq, q_n_uniq, q_slots, q_weights)
        tf = tf.reshape(tf.shape[-1])
        term = term.reshape(term.shape[-1])
        doc = doc.reshape(doc.shape[-1])
        doc_len = doc_len.reshape(doc_len.shape[-1])
        df_local = df.reshape(df.shape[-1])
        n_local = n_live.reshape(())
        live = live.reshape(live.shape[-1])
        len_local = len_sum.reshape(())
        doc_cap = doc_len.shape[0]

        if global_idf:
            df_eff = jax.lax.psum(df_local, ("docs", "terms"))
            n_eff = jax.lax.psum(n_local.astype(jnp.float32), "docs")
            total_len = jax.lax.psum(len_local, "docs")
            avgdl = total_len / jnp.maximum(n_eff, 1.0)
        else:
            df_eff = jax.lax.psum(df_local, "terms")
            n_eff = n_local.astype(jnp.float32)
            avgdl = len_local / jnp.maximum(n_eff, 1.0)

        doc_norms = None
        if model == "tfidf_cosine":
            sq = cosine_norms(tf, term, doc, df_eff, n_eff, doc_cap) ** 2
            doc_norms = jnp.sqrt(jax.lax.psum(sq, "terms"))

        partial = score_coo_impl(
            tf, term, doc, doc_len, df_eff, q,
            n_eff, avgdl, doc_norms, model=model, k1=k1, b=b, chunk=chunk)
        scores = jax.lax.psum(partial, "terms")
        return (scores * live[None, :])[None]           # [1, B, doc_cap]

    sharded = _shard_map(
        step,
        mesh=mesh,
        in_specs=(P("docs", "terms", None), P("docs", "terms", None),
                  P("docs", "terms", None), P("docs", None),
                  P("docs", "terms", None), P("docs"), P("docs", None),
                  P("docs"),
                  P(None), P(), P(None, None), P(None, None)),
        out_specs=P("docs", None, None),
        check_vma=False,
    )

    @jax.jit
    def scores(arrays: ShardedArrays, q: QueryBatch):
        return sharded(arrays.tf, arrays.term, arrays.doc, arrays.doc_len,
                       arrays.df, arrays.n_live, arrays.live,
                       arrays.len_sum,
                       jnp.asarray(q.uniq), jnp.asarray(q.n_uniq),
                       jnp.asarray(q.slots), jnp.asarray(q.weights))

    return scores


def build_ingest_batch(mesh: Mesh,
                       arrays: ShardedArrays,
                       new_docs_per_shard: list[list[dict[int, int]]],
                       lengths_per_shard: list[list[float]],
                       batch_chunk_cap: int,
                       raw_lengths_per_shard: list[list[float]] | None
                       = None):
    """Vectorize new documents into a device-ready ingest batch.

    ``new_docs_per_shard[d]`` holds the new docs placed on docs-shard d
    (already chosen by the balancer); they get local ids continuing after
    the shard's current live count. Entries are split over the terms axis
    the same way as the initial build (contiguous chunks).

    Raises if any block's free tail cannot hold a full batch window —
    ``dynamic_update_slice`` silently clamps out-of-range starts, so an
    oversized append would otherwise corrupt the front of the arrays.
    """
    D = mesh.shape["docs"]
    T = mesh.shape["terms"]
    C = batch_chunk_cap
    doc_cap = arrays.doc_cap
    chunk_cap = arrays.tf.shape[-1]
    used_now = host_value(arrays.nnz_used)
    if int(used_now.max()) + C > chunk_cap:
        raise ValueError(
            f"ingest batch (cap {C}) does not fit free tail "
            f"(used max {int(used_now.max())} of {chunk_cap}); "
            "compact/re-shard with a larger nnz capacity first")
    n_live_before = [int(x) for x in host_value(arrays.n_live)]
    max_new = max((len(d) for d in new_docs_per_shard), default=0)
    L = next_capacity(max(max_new, 1), 8)   # O(batch), not O(doc_cap)
    if max(n_live_before) + L > doc_cap:
        # the padded window would spill past the capacity even though the
        # real docs fit — retry with the tightest bucket before giving up
        L = next_capacity(max(max_new, 1), 1)
    if max(n_live_before) + L > doc_cap:
        raise ValueError("docs-shard over doc capacity; re-shard")
    new_tf = np.zeros((D, T, C), np.float32)
    new_term = np.zeros((D, T, C), np.int32)
    new_doc = np.full((D, T, C), doc_cap - 1, np.int32)   # sorted-padding
    new_count = np.zeros((D, T), np.int32)
    new_len = np.zeros((D, L), np.float32)
    new_docs = np.zeros(D, np.int32)
    # avgdl delta uses RAW lengths (doc_len may hold quantized values)
    raws = (raw_lengths_per_shard if raw_lengths_per_shard is not None
            else lengths_per_shard)
    new_len_sum = np.asarray([float(sum(r)) for r in raws], np.float32)
    for d in range(D):
        docs = new_docs_per_shard[d]
        lens = lengths_per_shard[d]
        tfs, terms, rows = [], [], []
        for i, counts in enumerate(docs):
            local = n_live_before[d] + i
            new_len[d, i] = lens[i]
            for t, f in sorted(counts.items()):
                if not 0 <= t < arrays.vocab_cap:
                    # the sharded path has no vocab growth; an out-of-range
                    # id would be clamped by XLA's gather at search time and
                    # silently score against another term's df
                    raise ValueError(
                        f"term id {t} outside vocab capacity "
                        f"{arrays.vocab_cap}; grow the vocabulary and "
                        "rebuild the sharded arrays first")
                terms.append(t)
                tfs.append(float(f))
                rows.append(local)
        new_docs[d] = len(docs)
        for t, (lo, hi) in enumerate(_split_ranges(len(tfs), T)):
            n = hi - lo
            if n > C:
                raise ValueError("ingest batch over chunk capacity")
            if n:
                new_tf[d, t, :n] = tfs[lo:hi]
                new_term[d, t, :n] = terms[lo:hi]
                new_doc[d, t, :n] = rows[lo:hi]
            new_count[d, t] = n

    def put(x, spec):
        return jax.device_put(x, NamedSharding(mesh, spec))

    return (put(new_tf, P("docs", "terms", None)),
            put(new_term, P("docs", "terms", None)),
            put(new_doc, P("docs", "terms", None)),
            put(new_count, P("docs", "terms")),
            put(new_len, P("docs", None)),
            put(new_docs, P("docs")),
            put(new_len_sum, P("docs")))


def make_sharded_ingest(mesh: Mesh):
    """Build the jitted distributed ingest step — on-device index growth.

    The streaming analog of the reference's upload path (file -> chosen
    worker -> index + commit, ``Leader.java:153-207`` / ``Worker.java:125-
    146``), but batched: each docs-shard receives a block of new postings
    (host-vectorized, already placed by the balancer) and appends them into
    its device arrays without recompilation or host round-trips:

        tf/term/doc: dynamic-update-slice at the shard's append cursor
        df:          += segment-sum of the new entries
        doc_len:     new lengths written at the live cursor (new local ids
                     are contiguous from n_live, so the delta is O(batch))
        n_live:      += new document count

    New-entry padding must be tf 0 / term 0 / doc ``doc_cap - 1`` (the
    sorted-padding convention) — writing those into the free region is a
    no-op by construction. Overflowing a capacity bucket is the host's job
    to detect (re-shard with bigger caps).

    Returned callable:
        ingest(arrays, new_tf [D,T,C], new_term, new_doc, new_count [D,T],
               new_len [D,L], new_docs [D]) -> ShardedArrays
    """

    def step(tf, term, doc, doc_len, df, n_live, nnz_used, live, len_sum,
             new_tf, new_term, new_doc, new_count, new_len, new_docs,
             new_len_sum):
        tf = tf.reshape(tf.shape[-1])
        term = term.reshape(term.shape[-1])
        doc = doc.reshape(doc.shape[-1])
        doc_len = doc_len.reshape(doc_len.shape[-1])
        df = df.reshape(df.shape[-1])
        n_live = n_live.reshape(())
        used = nnz_used.reshape(())
        live = live.reshape(live.shape[-1])
        len_sum = len_sum.reshape(())
        new_tf = new_tf.reshape(new_tf.shape[-1])
        new_term = new_term.reshape(new_term.shape[-1])
        new_doc = new_doc.reshape(new_doc.shape[-1])
        new_count = new_count.reshape(())
        new_len = new_len.reshape(new_len.shape[-1])
        new_docs = new_docs.reshape(())
        new_len_sum = new_len_sum.reshape(())

        vocab_cap = df.shape[0]
        tf2 = jax.lax.dynamic_update_slice(tf, new_tf, (used,))
        term2 = jax.lax.dynamic_update_slice(term, new_term, (used,))
        doc2 = jax.lax.dynamic_update_slice(doc, new_doc, (used,))
        df2 = df + jax.ops.segment_sum(
            (new_tf > 0).astype(jnp.float32), new_term,
            num_segments=vocab_cap)
        # new docs occupy the contiguous range starting at the live cursor;
        # their prior lengths are zero, so an overwrite == an add
        doc_len2 = jax.lax.dynamic_update_slice(doc_len, new_len, (n_live,))
        # newly appended slots become live (the batch window may be wider
        # than the real doc count, so mark exactly [n_live, n_live+new))
        slot = jnp.arange(live.shape[0], dtype=jnp.int32)
        live2 = jnp.where((slot >= n_live) & (slot < n_live + new_docs),
                          jnp.float32(1.0), live)
        n2 = n_live + new_docs
        used2 = used + new_count
        return (tf2[None, None], term2[None, None], doc2[None, None],
                doc_len2[None], df2[None, None], n2[None],
                used2[None, None], live2[None],
                (len_sum + new_len_sum)[None])

    sharded = _shard_map(
        step,
        mesh=mesh,
        in_specs=(P("docs", "terms", None), P("docs", "terms", None),
                  P("docs", "terms", None), P("docs", None),
                  P("docs", "terms", None), P("docs"), P("docs", "terms"),
                  P("docs", None), P("docs"),
                  P("docs", "terms", None), P("docs", "terms", None),
                  P("docs", "terms", None), P("docs", "terms"),
                  P("docs", None), P("docs"), P("docs")),
        out_specs=(P("docs", "terms", None), P("docs", "terms", None),
                   P("docs", "terms", None), P("docs", None),
                   P("docs", "terms", None), P("docs"),
                   P("docs", "terms"), P("docs", None), P("docs")),
        check_vma=False,
    )

    @jax.jit
    def ingest(arrays: ShardedArrays, new_tf, new_term, new_doc, new_count,
               new_len, new_docs, new_len_sum):
        (tf, term, doc, doc_len, df, n_live, nnz_used, live,
         len_sum) = sharded(
            arrays.tf, arrays.term, arrays.doc, arrays.doc_len, arrays.df,
            arrays.n_live, arrays.nnz_used, arrays.live, arrays.len_sum,
            new_tf, new_term, new_doc, new_count, new_len, new_docs,
            new_len_sum)
        return ShardedArrays(
            tf=tf, term=term, doc=doc, doc_len=doc_len, df=df,
            n_live=n_live, nnz_used=nnz_used, live=live, len_sum=len_sum,
            doc_cap=arrays.doc_cap, vocab_cap=arrays.vocab_cap)

    return ingest


def with_live_mask(mesh: Mesh, arrays: ShardedArrays,
                   live_host: np.ndarray) -> ShardedArrays:
    """Replace the tombstone mask from a host [D, doc_cap] f32 array.

    Deletes are rare next to queries, so the mask is rebuilt host-side and
    re-placed (one [D, doc_cap] transfer) rather than scattered on device —
    the postings arrays are untouched, exactly like flipping bits in
    Lucene's deleted-docs bitmap without rewriting segments.
    """
    import dataclasses
    live = jax.device_put(live_host.astype(np.float32),
                          NamedSharding(mesh, P("docs", None)))
    return dataclasses.replace(arrays, live=live)


# ---- ShardedArrays checkpoint (mesh-scale Worker.java:88 commit) ----

_CKPT_FIELDS = ("tf", "term", "doc", "doc_len", "df", "n_live",
                "nnz_used", "live", "len_sum")
_CKPT_SPECS = {
    "tf": P("docs", "terms", None), "term": P("docs", "terms", None),
    "doc": P("docs", "terms", None), "doc_len": P("docs", None),
    "df": P("docs", "terms", None), "n_live": P("docs"),
    "nnz_used": P("docs", "terms"), "live": P("docs", None),
    "len_sum": P("docs"),
}


def save_sharded_arrays(arrays: ShardedArrays, path: str) -> None:
    """Write the full device state to one ``.npz`` (atomic via rename).

    The host copy of every field is fetched once; restore re-places the
    blocks on any mesh with the same (D, T) shape.
    """
    from tfidf_tpu.utils import storage
    data = {f: np.asarray(getattr(arrays, f)) for f in _CKPT_FIELDS}
    data["meta"] = np.asarray([arrays.doc_cap, arrays.vocab_cap], np.int64)
    tmp = path + ".part"
    storage.savez(tmp, **data)
    storage.replace(tmp, path)


def load_sharded_arrays(path: str, mesh: Mesh) -> ShardedArrays:
    """Restore a :func:`save_sharded_arrays` checkpoint onto ``mesh``.

    The mesh must have the same (docs, terms) shape the checkpoint was
    taken with (the leading axes of the saved blocks).
    """
    data = np.load(path)
    D, T = data["tf"].shape[:2]
    if (mesh.shape["docs"], mesh.shape["terms"]) != (D, T):
        raise ValueError(
            f"checkpoint was taken on a ({D}, {T}) mesh; restoring onto "
            f"{dict(mesh.shape)} requires a rebuild from documents")
    doc_cap, vocab_cap = (int(x) for x in data["meta"])
    kw = {f: jax.device_put(data[f], NamedSharding(mesh, _CKPT_SPECS[f]))
          for f in _CKPT_FIELDS}
    return ShardedArrays(doc_cap=doc_cap, vocab_cap=vocab_cap, **kw)

from tfidf_tpu.models.base import ScoringModel, get_model
from tfidf_tpu.models.bm25 import BM25Model, int_to_byte4, byte4_to_int
from tfidf_tpu.models.tfidf import TfidfModel, TfidfCosineModel

__all__ = [
    "ScoringModel",
    "get_model",
    "BM25Model",
    "TfidfModel",
    "TfidfCosineModel",
    "int_to_byte4",
    "byte4_to_int",
]

"""Scoring model interface.

The reference hard-codes one model: whatever Lucene's default similarity is
(BM25 since Lucene 6 — so the "TF-IDF" system actually scores BM25,
``Worker.java:222-241``, SURVEY.md §2 "Scoring helper"). Here the model is a
first-class, swappable family: BM25 (Lucene-parity option included) and
TF-IDF variants share one device scoring kernel
(:func:`tfidf_tpu.ops.scoring.score_coo_batch`) parameterized by the model.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class ScoringModel:
    """Base: maps config onto kernel parameters and host-side transforms."""

    kind: str = "base"

    @property
    def needs_norms(self) -> bool:
        """Whether the kernel needs per-doc L2 norms (cosine models)."""
        return False

    def score_kwargs(self) -> dict:
        """Static kwargs for ``score_coo_batch`` (selects the weight fn)."""
        return {"model": self.kind}

    def transform_doc_len(self, doc_len: np.ndarray) -> np.ndarray:
        """Hook for norm-encoding document lengths (Lucene parity)."""
        return doc_len

    def query_weights(self, term_counts: dict[int, int]) -> dict[int, float]:
        """Per-term query-side weight. Default: term multiplicity, matching
        the reference's QueryParser output (duplicate terms become duplicate
        TermQuery clauses whose scores add, ``Worker.java:226-230``)."""
        return {t: float(c) for t, c in term_counts.items()}


def get_model(name: str, *, k1: float = 1.2, b: float = 0.75,
              lucene_parity: bool = False) -> ScoringModel:
    from tfidf_tpu.models.bm25 import BM25Model
    from tfidf_tpu.models.tfidf import TfidfCosineModel, TfidfModel

    if name == "bm25":
        return BM25Model(k1=k1, b=b, lucene_parity=lucene_parity)
    if name == "tfidf":
        return TfidfModel()
    if name == "tfidf_cosine":
        return TfidfCosineModel()
    raise ValueError(f"unknown scoring model {name!r}")

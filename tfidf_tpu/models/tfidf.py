"""TF-IDF scoring models — the family the reference is named after.

``tfidf``: raw dot product of tf·idf document weights with query term
multiplicities (smoothed idf, finite everywhere). ``tfidf_cosine``:
additionally L2-normalizes each document's tf·idf vector (the "cosine
ranking" named in the north star, /root/repo/BASELINE.json) — norms are
recomputed at commit time because they depend on global document frequency.
"""

from __future__ import annotations

from dataclasses import dataclass

from tfidf_tpu.models.base import ScoringModel


@dataclass(frozen=True)
class TfidfModel(ScoringModel):
    kind: str = "tfidf"


@dataclass(frozen=True)
class TfidfCosineModel(ScoringModel):
    kind: str = "tfidf_cosine"

    @property
    def needs_norms(self) -> bool:
        return True

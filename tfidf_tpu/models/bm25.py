"""BM25 — the reference system's true scoring function.

Lucene 9's default similarity is ``BM25Similarity`` (k1=1.2, b=0.75); the
reference never overrides it, so every worker scores BM25 against its local
shard (``Worker.java:222-241``). Two fidelity levels:

* exact BM25 with true document lengths (default — strictly better);
* ``lucene_parity=True`` additionally reproduces Lucene's lossy 1-byte norm
  encoding (``SmallFloat.intToByte4``): document lengths round-trip through
  a 4-mantissa-bit byte code before entering the length normalization, which
  is required for score-identical parity with the Java system.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from tfidf_tpu.models.base import ScoringModel


# --- SmallFloat byte-4 codec (org.apache.lucene.util.SmallFloat) ----------

def _long_to_int4(i: int) -> int:
    if i < 0:
        raise ValueError("negative length")
    num_bits = i.bit_length()
    if num_bits < 4:
        return i
    shift = num_bits - 4
    encoded = (i >> shift) & 0x07      # drop the implicit leading 1 bit
    encoded |= (shift + 1) << 3
    return encoded


def _int4_to_long(i: int) -> int:
    bits = i & 0x07
    shift = (i >> 3) - 1
    return bits if shift == -1 else (bits | 0x08) << shift


_MAX_INT4 = _long_to_int4(2**31 - 1)
_NUM_FREE_VALUES = 255 - _MAX_INT4


def int_to_byte4(i: int) -> int:
    """Lossy int -> unsigned byte with 4 mantissa bits (values 0..39 exact)."""
    if i < _NUM_FREE_VALUES:
        return i
    return _NUM_FREE_VALUES + _long_to_int4(i - _NUM_FREE_VALUES)


def byte4_to_int(b: int) -> int:
    if b < _NUM_FREE_VALUES:
        return b
    return _NUM_FREE_VALUES + _int4_to_long(b - _NUM_FREE_VALUES)


def quantize_length(dl: int) -> int:
    """Length as BM25 sees it after Lucene's norm round-trip."""
    return byte4_to_int(int_to_byte4(int(dl)))


_QUANT_TABLE = None


def _quant_table() -> np.ndarray:
    global _QUANT_TABLE
    if _QUANT_TABLE is None:
        # decode table over all 256 byte codes; encode via searchsorted
        _QUANT_TABLE = np.array([byte4_to_int(b) for b in range(256)],
                                dtype=np.int64)
    return _QUANT_TABLE


def quantize_lengths(dl: np.ndarray) -> np.ndarray:
    """Vectorized quantize_length over an int array."""
    table = _quant_table()
    # codes are monotonically increasing in dl; find the largest decoded
    # value <= encode(dl) by emulating encode: encode is monotone, and
    # round-trip maps dl to the table entry at its encoded byte.
    codes = np.searchsorted(table, dl, side="right") - 1
    return table[np.clip(codes, 0, 255)]


@dataclass(frozen=True)
class BM25Model(ScoringModel):
    kind: str = "bm25"
    k1: float = 1.2
    b: float = 0.75
    lucene_parity: bool = False

    def score_kwargs(self) -> dict:
        return {"model": "bm25", "k1": self.k1, "b": self.b}

    def transform_doc_len(self, doc_len: np.ndarray) -> np.ndarray:
        if not self.lucene_parity:
            return doc_len
        out = quantize_lengths(doc_len.astype(np.int64))
        return out.astype(np.float32)

"""Checkpoint save/restore at 1M docs (VERDICT r3 #5).

Round 3's restore replayed 1M documents through a per-doc Python loop
(39.2s end-to-end); the packed bulk path (engine/checkpoint.py
``_to_coo_packed``) builds the index arrays directly from ``docs.npz``.
This probe measures save + restore + parity at the north-star shape and
records the numbers for PERF.md.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(os.path.dirname(__file__), ".jax_cache"))

from bench import NS_VOCAB, make_doc_arrays, make_queries  # noqa: E402

N_DOCS = int(os.environ.get("PROBE_DOCS", 1_000_000))
AVG_LEN = 120


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def run_mode(mode: str, corpus, queries) -> dict:
    """Build -> save -> restore -> parity for one index mode.

    ``mode="segments"`` is the streaming flagship: ingest in 100k-doc
    commit waves (a real segment list + tiered merges), then restore
    through the segment-level fast path (segstate.npz)."""
    from tfidf_tpu.engine import Engine
    from tfidf_tpu.engine.checkpoint import (load_checkpoint,
                                             save_checkpoint)
    from tfidf_tpu.utils.config import Config

    offsets, ids, tfs, lengths = corpus
    cfg = Config(query_batch=64,
                 index_mode="segments" if mode == "segments" else "rebuild")
    engine = Engine(cfg)
    for i in range(NS_VOCAB):
        engine.vocab.add(f"t{i}")
    t0 = time.perf_counter()
    add = engine.index.add_document_arrays
    for i in range(N_DOCS):
        lo, hi = offsets[i], offsets[i + 1]
        add(f"d{i}", ids[lo:hi], tfs[lo:hi], float(lengths[i]))
        if mode == "segments" and (i + 1) % 100_000 == 0:
            engine.commit()
    engine.commit()
    if mode == "segments":
        engine.index.wait_for_merges()
        engine.commit()
    log(f"[ckpt:{mode}] built {N_DOCS}-doc engine in "
        f"{time.perf_counter()-t0:.0f}s")
    want = engine.search_batch(queries, k=10)

    tmp = tempfile.mkdtemp(prefix=f"probe_ckpt_{mode}_")
    try:
        t0 = time.perf_counter()
        save_checkpoint(engine, tmp)
        save_s = time.perf_counter() - t0
        n_segments = (len(engine.index._segments)
                      if mode == "segments" else None)
        del engine
        t0 = time.perf_counter()
        restored = load_checkpoint(tmp, cfg)
        load_s = time.perf_counter() - t0
        if mode == "segments":
            assert len(restored.index._segments) == n_segments, \
                "restore must reproduce the segment list, not rebuild"
        t0 = time.perf_counter()
        got = restored.search_batch(queries, k=10)
        first_search_s = time.perf_counter() - t0
        for w, g in zip(want, got):
            assert [h.name for h in w] == [h.name for h in g]
            np.testing.assert_allclose([h.score for h in w],
                                       [h.score for h in g], rtol=1e-6)
        out = {"n_docs": N_DOCS,
               "save_s": round(save_s, 1),
               "restore_s": round(load_s, 1),
               "first_search_s": round(first_search_s, 1),
               "parity_checked": True}
        if n_segments is not None:
            out["segments"] = n_segments
        log(f"[ckpt:{mode}] save {save_s:.1f}s, restore {load_s:.1f}s, "
            f"first search {first_search_s:.1f}s, top-10 identical "
            f"on {len(queries)} queries")
        return out
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def main():
    rng = np.random.default_rng(0)
    corpus = make_doc_arrays(rng, N_DOCS, NS_VOCAB, AVG_LEN)
    queries = make_queries(rng, NS_VOCAB, 64)
    modes = os.environ.get("PROBE_MODES", "shard,segments").split(",")
    out = {"nnz": int(corpus[1].shape[0])}
    for mode in modes:
        out[mode] = run_mode(mode.strip(), corpus, queries)
    print(json.dumps(out))


if __name__ == "__main__":
    main()

"""Checkpoint save/restore at 1M docs (VERDICT r3 #5).

Round 3's restore replayed 1M documents through a per-doc Python loop
(39.2s end-to-end); the packed bulk path (engine/checkpoint.py
``_to_coo_packed``) builds the index arrays directly from ``docs.npz``.
This probe measures save + restore + parity at the north-star shape and
records the numbers for PERF.md.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(os.path.dirname(__file__), ".jax_cache"))

from bench import NS_VOCAB, make_doc_arrays, make_queries  # noqa: E402

N_DOCS = int(os.environ.get("PROBE_DOCS", 1_000_000))
AVG_LEN = 120


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def main():
    from tfidf_tpu.engine import Engine
    from tfidf_tpu.engine.checkpoint import (load_checkpoint,
                                             save_checkpoint)
    from tfidf_tpu.utils.config import Config

    rng = np.random.default_rng(0)
    offsets, ids, tfs, lengths = make_doc_arrays(rng, N_DOCS, NS_VOCAB,
                                                 AVG_LEN)
    engine = Engine(Config(query_batch=64))
    for i in range(NS_VOCAB):
        engine.vocab.add(f"t{i}")
    t0 = time.perf_counter()
    add = engine.index.add_document_arrays
    for i in range(N_DOCS):
        lo, hi = offsets[i], offsets[i + 1]
        add(f"d{i}", ids[lo:hi], tfs[lo:hi], float(lengths[i]))
    engine.commit()
    log(f"[ckpt] built {N_DOCS}-doc engine in "
        f"{time.perf_counter()-t0:.0f}s")
    queries = make_queries(rng, NS_VOCAB, 64)
    want = engine.search_batch(queries, k=10)

    tmp = tempfile.mkdtemp(prefix="probe_ckpt_")
    try:
        t0 = time.perf_counter()
        save_checkpoint(engine, tmp)
        save_s = time.perf_counter() - t0
        del engine
        t0 = time.perf_counter()
        restored = load_checkpoint(tmp, Config(query_batch=64))
        load_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        got = restored.search_batch(queries, k=10)
        first_search_s = time.perf_counter() - t0
        for w, g in zip(want, got):
            assert [h.name for h in w] == [h.name for h in g]
            np.testing.assert_allclose([h.score for h in w],
                                       [h.score for h in g], rtol=1e-6)
        out = {"n_docs": N_DOCS, "nnz": int(ids.shape[0]),
               "save_s": round(save_s, 1),
               "restore_s": round(load_s, 1),
               "first_search_s": round(first_search_s, 1),
               "parity_checked": True}
        log(f"[ckpt] save {save_s:.1f}s, restore {load_s:.1f}s, "
            f"first search {first_search_s:.1f}s, top-10 identical "
            f"on {len(queries)} queries")
        print(json.dumps(out))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()

# Test / chaos job targets.
#
#   make test         tier-1: fast deterministic suite (what the driver
#                     runs and .github/workflows/tier1.yml replicates);
#                     includes the deterministic subsets of
#                     tests/test_resilience.py and
#                     tests/test_coordination_durability.py
#   make chaos        slow probabilistic chaos job: fault injection armed
#                     on worker RPCs, heartbeats, and reconciles
#                     (tests/test_resilience.py -m slow)
#   make chaos-coord  slow coordination-durability chaos job: SIGKILL +
#                     restart of substrate members (subprocess
#                     coordinators) mid-traffic
#                     (tests/test_coordination_durability.py -m slow)
#   make chaos-replica  slow replication chaos job: kill -9 a worker
#                     subprocess mid-workload under churn, assert every
#                     in-flight and subsequent search returns the
#                     complete result set in exact parity with a
#                     single-node oracle; plus SIGKILL of the whole
#                     coordinator ensemble with the placement map
#                     intact (tests/test_replication.py -m slow)
#   make chaos-rebalance  slow elastic-data-plane chaos job: kill -9
#                     the migration SOURCE at leader.rebalance_copy and
#                     the TARGET at leader.rebalance_flip mid-drain,
#                     plus a hard leader kill mid-migration, all under
#                     a concurrent search workload asserting exact
#                     single-node-oracle merge parity on every response
#                     (tests/test_rebalance.py -m slow)
#   make chaos-overload  slow overload chaos job: 2x-overload zipfian
#                     closed loop against the admission front door with
#                     a real mid-run worker kill -9 AND a cache-
#                     invalidating upsert — shed rate rises, p99 of
#                     ADMITTED interactive queries stays bounded, every
#                     admitted result in exact single-node-oracle
#                     parity (tests/test_admission.py -m slow)
#   make chaos-autopilot  slow SLO-autopilot chaos job: step-change
#                     (1x -> 2x) zipfian closed loop with the
#                     autopilot enabled at fast cadence and a mid-run
#                     worker kill -9 — the control loop must make real
#                     adjustments, converge WITHOUT oscillation (no
#                     sign-flapping adjustments), keep admitted p99
#                     bounded, and revert exactly to static config on
#                     the kill switch (tests/test_autopilot.py -m slow)
#   make chaos-router  slow query-plane chaos job: 2x zipfian load
#                     through two stateless routers while a router AND
#                     the leader are killed -9 mid-workload — the
#                     surviving router keeps serving, every admitted
#                     read is exact single-node-oracle parity or
#                     honestly degraded (X-Scatter-Degraded), and the
#                     tier heals (tests/test_router.py -m slow)
#   make chaos-powerloss  slow whole-cluster power-loss chaos job: an
#                     upload/search workload with the DISK nemesis
#                     armed (torn writes on the document stores) while
#                     kill -9 hits EVERY node AND the coordinator at
#                     once; full restart on the same dirs must show
#                     zero acked-upload loss and exact single-node-
#                     oracle parity on every post-restart search
#                     (tests/test_storage.py -m slow)
#   make scrub        offline storage-integrity verification: every
#                     checkpoint version's manifest + the placed-docs
#                     CRC ledger (python -m tfidf_tpu scrub; exit 1 on
#                     corruption). POST /admin/scrub runs the same
#                     pass on a live node.
#   make chaos-partition  slow jepsen-style partition chaos job: a
#                     concurrent upsert/delete/search workload while
#                     the network nemesis (cluster/nemesis.py) deposes
#                     the node leader (control-plane cut, data plane
#                     intact — the split-brain fence case), splits the
#                     3-member coordinator ensemble, one-way-isolates
#                     a worker, and flaps the full mesh; after heal:
#                     exact single-node-oracle parity, zero acked-write
#                     loss, zero stale-epoch writes accepted
#                     (tests/test_partition.py -m slow)
#   make chaos-upgrade  slow zero-downtime-fleet-evolution chaos job:
#                     a rolling restart of the WHOLE fleet (workers ->
#                     router -> leader) onto a raised proto floor under
#                     live zipfian read + write load, with the version-
#                     skew nemesis stripping X-Proto-Version on one
#                     link, a partition, and an fsync-EIO storage fault
#                     mid-roll — zero acked-write loss, bounded shed,
#                     zero proto rejections for stamped clients, exact
#                     single-node-oracle parity at the end, and the
#                     upgraded fleet 426-rejects unstamped (implicit-v1)
#                     traffic (tests/test_upgrade.py -m slow)
#   make faults       list every registered fault point (chaos configs
#                     should be validated against this — see
#                     utils/faults.py)

#   make probe-overlap  fetch/compute overlap isolation experiment
#                     (VERDICT r5 Weak #3): two independently fetchable
#                     device programs + the pipeline executor on a fake
#                     workload; writes PROBE_OVERLAP.json
#   make bench-overload  zipfian closed-loop overload bench (1x and 2x
#                     saturating concurrency, per-lane p50/p99 latency,
#                     shed rate, cache hit rate); writes OVERLOAD.json
#   make bench-routers  multi-router scale-out bench: the same zipfian
#                     closed loop at equal offered load through 1, 2,
#                     and 4 stateless routers; admitted interactive
#                     q/s must scale (2 routers >= 1.6x the 1-router
#                     baseline); writes BENCH_r07.json
#   make bench-kernel  r14 kernel-headroom bench: A-build v3 vs v4 vs
#                     the XLA oracle (parity gated in-run), the
#                     analytic A-build op-count model, and steady
#                     commit cost incremental-df vs full-recompute
#                     across a 4x corpus sweep on the mesh-ELL and
#                     segments indexes (df_full_recomputes witness
#                     asserted zero); writes BENCH_r09.json
#   make bench-replay  r16 capture/replay bench: a zipfian closed loop
#                     through a router with the durable request log
#                     (capture) enabled, then the SAME traffic re-driven
#                     open-loop at recorded offsets against a fresh
#                     router — fidelity gated in-run (every captured
#                     admitted request must replay admitted); writes
#                     BENCH_r10.json
#   make bench-hybrid r17 hybrid-retrieval bench: batched dense q/s
#                     (with the achieved model-flop rate) beside the
#                     sparse plane on the same engine/stream, a
#                     sparse/dense/hybrid latency table, and
#                     fused-vs-sparse relevance deltas (MRR@10 /
#                     recall@10) on the synthetic MS MARCO-style
#                     slice; backend stamped honestly; writes
#                     BENCH_r11.json
#   make chaos-hybrid slow hybrid chaos job: zipfian hybrid/dense
#                     load with a worker's data plane killed
#                     mid-scatter — every reply exact or honestly
#                     X-Scatter-Degraded, never silently partial
#                     (tests/test_hybrid.py -m slow)
#   make bench-tier   r18 tiered-postings bench: a synthetic corpus
#                     provably larger than the hot-set HBM budget,
#                     phased zipfian search with cold-segment
#                     skip rate, hot-tier hit rate, upload-ring stall
#                     time, flat steady-state ingest dps
#                     (df_full_recomputes asserted zero), and exact
#                     top-k parity vs the untiered oracle gated on
#                     every phase; writes BENCH_r12.json
#   make chaos-tier   slow tiered-storage chaos job: the disk nemesis
#                     flips bytes in a cold spill file mid-query — the
#                     rotten spill must be quarantined, repaired from
#                     the host replica, and every search stays in
#                     exact untiered-oracle parity
#                     (tests/test_tiering.py -m slow)
#   make bench-compute  r20 degraded-mode bench: the same measured
#                     search loop on the healthy device path and on
#                     the host-fallback path (device forced sick via
#                     the nemesis), q/s + p50/p99 side by side with
#                     in-run bit-parity gating and the steady-state
#                     zero-recompile witness on the healthy leg;
#                     writes BENCH_r13.json
#   make chaos-compute  slow compute-plane chaos job: zipfian load
#                     over a subprocess fleet while the device nemesis
#                     OOMs one worker's every dispatch (host-fallback
#                     degraded serving, honestly stamped
#                     X-Compute-Degraded), slow-wedges another, and
#                     poisons a query's rows on two replicas — every
#                     200 exact-parity-or-honestly-stamped, zero
#                     acked-write loss, the poison fingerprint
#                     quarantined (front-door 422) after exactly two
#                     distinct replica verdicts, full recovery after
#                     heal (tests/test_compute_chaos.py -m slow)

#   make trace-demo   zero-to-aha for the tracing layer: spin a small
#                     in-process cluster, kill a worker mid-request,
#                     print the rendered trace timeline showing the
#                     failed scatter.worker span and the scatter.slice
#                     failover that kept the results complete
#                     (tools/trace_demo.py)

#   make graftcheck   project-native static analysis (tools/graftcheck):
#                     lock-graph/deadlock, jit-purity, registry drift,
#                     resilience coverage, the wire-contract protocol
#                     passes (endpoint/header/status/seam drift), and
#                     the dead-symbol sweep — against the committed
#                     allowlist/baseline; new findings fail. Use
#                     `python -m tools.graftcheck --only protocol` for
#                     fast iteration on one analyzer.
#   make lockdep      the chaos/resilience/cluster suites under the
#                     runtime lockdep witness (instrumented Lock):
#                     fails on any inversion or any ordering the
#                     static lock graph cannot explain
#   make protocol-witness  the router + partition suites with the
#                     handler classes instrumented (runtime protocol
#                     witness): every observed (endpoint, method,
#                     status, headers) exchange must be explained by
#                     the static wire contract, and the core
#                     scatter/mutation surface must actually be
#                     exercised — lockdep-style mutual validation
#   make devicecheck  the device-hygiene static passes alone
#                     (tools/graftcheck/devicecheck.py): jit-cache
#                     discipline, transfer hygiene in the hot serving
#                     cone, donation audit — fast iteration target;
#                     `make graftcheck` runs them too
#   make device-witness  the engine/pipeline/tiering/hybrid suites
#                     under the runtime device witness (XLA compile
#                     events + instrumented np fetchers): every
#                     observed device->host transfer must be explained
#                     by the static devicecheck cone (named fetch/bulk
#                     stages or an allowlisted-with-reason site);
#                     vacuous runs fail (GRAFTCHECK_DEVICE_MIN)
#   make check        graftcheck + tier-1 in one shot

PYTEST_FLAGS := -q --continue-on-collection-errors -p no:cacheprovider

.PHONY: test chaos chaos-coord chaos-replica chaos-rebalance \
        chaos-overload chaos-partition chaos-autopilot chaos-router \
        chaos-powerloss chaos-upgrade chaos-hybrid chaos-tier \
        chaos-compute scrub \
        faults bench bench-overload bench-routers bench-kernel \
        bench-replay bench-hybrid bench-tier bench-compute \
        probe-overlap \
        graftcheck lockdep protocol-witness devicecheck \
        device-witness check trace-demo

test:
	JAX_PLATFORMS=cpu python -m pytest tests/ $(PYTEST_FLAGS) -m 'not slow'

graftcheck:
	python -m tools.graftcheck

# Suite choice: resilience + cluster + graftcheck cover every
# multi-lock ordering in the tree (the graftcheck suite drives a
# durable ensemble coordinator too) and are timing-stable under the
# instrumented Lock's overhead. test_coordination_durability's
# randomized-election Raft tests are NOT run instrumented — their 1s
# election margins flake under the added per-acquisition cost on
# 2-core CI runners; they still run uninstrumented in tier-1.
lockdep:
	JAX_PLATFORMS=cpu GRAFTCHECK_LOCKDEP=1 python -m pytest \
	  tests/test_resilience.py tests/test_cluster.py \
	  tests/test_replication.py tests/test_rebalance.py \
	  tests/test_admission.py tests/test_partition.py \
	  tests/test_observability.py tests/test_autopilot.py \
	  tests/test_router.py tests/test_storage.py \
	  tests/test_commit_stats.py tests/test_upgrade.py \
	  tests/test_graftcheck.py tests/test_hybrid.py \
	  tests/test_tiering.py tests/test_compute_chaos.py \
	  $(PYTEST_FLAGS) -m 'not slow'

# Suite choice: test_router drives the stateless-router tier (reads,
# proxied writes, sheds, downloads), test_partition drives the
# fence/nemesis wire surface, and test_hybrid drives the staged v3
# surface (mode/fusion fields, 2n replies, X-Search-Stages) — together
# they exercise the core scatter/mutation contract rows
# (CORE_EXERCISED in tools/graftcheck/protocol_witness.py) the
# witness requires.
protocol-witness:
	JAX_PLATFORMS=cpu GRAFTCHECK_PROTOCOL=1 python -m pytest \
	  tests/test_router.py tests/test_partition.py \
	  tests/test_graftcheck.py tests/test_hybrid.py \
	  $(PYTEST_FLAGS) -m 'not slow'

devicecheck:
	python -m tools.graftcheck --only devicecheck

# Suite choice: engine + pipeline + tiering + hybrid are the suites
# that drive the hot serving cone (searcher dispatch, pipeline
# dispatch/fetch, tiering upload ring, dense plane) — the paths whose
# transfers devicecheck reasons about statically. test_devicecheck's
# own steady-state gate additionally asserts zero post-warmup XLA
# recompiles; the suite-wide witness checks transfers only (per-test
# compile churn is expected across a suite).
device-witness:
	JAX_PLATFORMS=cpu GRAFTCHECK_DEVICE=1 GRAFTCHECK_DEVICE_MIN=1 \
	  python -m pytest \
	  tests/test_engine.py tests/test_pipeline.py \
	  tests/test_tiering.py tests/test_hybrid.py \
	  tests/test_compute_chaos.py \
	  $(PYTEST_FLAGS) -m 'not slow'

trace-demo:
	JAX_PLATFORMS=cpu python tools/trace_demo.py

check: graftcheck test

chaos:
	JAX_PLATFORMS=cpu python -m pytest tests/test_resilience.py $(PYTEST_FLAGS) -m slow

chaos-coord:
	JAX_PLATFORMS=cpu python -m pytest tests/test_coordination_durability.py $(PYTEST_FLAGS) -m slow

chaos-replica:
	JAX_PLATFORMS=cpu python -m pytest tests/test_replication.py $(PYTEST_FLAGS) -m slow

chaos-rebalance:
	JAX_PLATFORMS=cpu python -m pytest tests/test_rebalance.py $(PYTEST_FLAGS) -m slow

chaos-overload:
	JAX_PLATFORMS=cpu python -m pytest tests/test_admission.py $(PYTEST_FLAGS) -m slow

chaos-partition:
	JAX_PLATFORMS=cpu python -m pytest tests/test_partition.py $(PYTEST_FLAGS) -m slow

chaos-autopilot:
	JAX_PLATFORMS=cpu python -m pytest tests/test_autopilot.py $(PYTEST_FLAGS) -m slow

chaos-router:
	JAX_PLATFORMS=cpu python -m pytest tests/test_router.py $(PYTEST_FLAGS) -m slow

chaos-powerloss:
	JAX_PLATFORMS=cpu python -m pytest tests/test_storage.py $(PYTEST_FLAGS) -m slow

chaos-upgrade:
	JAX_PLATFORMS=cpu python -m pytest tests/test_upgrade.py $(PYTEST_FLAGS) -m slow

chaos-hybrid:
	JAX_PLATFORMS=cpu python -m pytest tests/test_hybrid.py $(PYTEST_FLAGS) -m slow

chaos-tier:
	JAX_PLATFORMS=cpu python -m pytest tests/test_tiering.py $(PYTEST_FLAGS) -m slow

chaos-compute:
	JAX_PLATFORMS=cpu python -m pytest tests/test_compute_chaos.py $(PYTEST_FLAGS) -m slow

scrub:
	python -m tfidf_tpu scrub

faults:
	python -m tfidf_tpu faults list

bench:
	python bench.py

probe-overlap:
	python probe_overlap.py

bench-overload:
	BENCH_OUT=OVERLOAD.json python bench.py --overload

bench-routers:
	BENCH_OUT=BENCH_r07.json python bench.py --routers

bench-kernel:
	BENCH_OUT=BENCH_r09.json python bench.py --kernel

bench-replay:
	BENCH_OUT=BENCH_r10.json python bench.py --replay

bench-hybrid:
	BENCH_OUT=BENCH_r11.json python bench.py --hybrid

bench-tier:
	BENCH_OUT=BENCH_r12.json python bench.py --tier

bench-compute:
	BENCH_OUT=BENCH_r13.json python bench.py --compute

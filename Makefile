# Test / chaos job targets.
#
#   make test         tier-1: fast deterministic suite (what the driver
#                     runs and .github/workflows/tier1.yml replicates);
#                     includes the deterministic subsets of
#                     tests/test_resilience.py and
#                     tests/test_coordination_durability.py
#   make chaos        slow probabilistic chaos job: fault injection armed
#                     on worker RPCs, heartbeats, and reconciles
#                     (tests/test_resilience.py -m slow)
#   make chaos-coord  slow coordination-durability chaos job: SIGKILL +
#                     restart of substrate members (subprocess
#                     coordinators) mid-traffic
#                     (tests/test_coordination_durability.py -m slow)
#   make faults       list every registered fault point (chaos configs
#                     should be validated against this — see
#                     utils/faults.py)

#   make probe-overlap  fetch/compute overlap isolation experiment
#                     (VERDICT r5 Weak #3): two independently fetchable
#                     device programs + the pipeline executor on a fake
#                     workload; writes PROBE_OVERLAP.json

PYTEST_FLAGS := -q --continue-on-collection-errors -p no:cacheprovider

.PHONY: test chaos chaos-coord faults bench probe-overlap

test:
	JAX_PLATFORMS=cpu python -m pytest tests/ $(PYTEST_FLAGS) -m 'not slow'

chaos:
	JAX_PLATFORMS=cpu python -m pytest tests/test_resilience.py $(PYTEST_FLAGS) -m slow

chaos-coord:
	JAX_PLATFORMS=cpu python -m pytest tests/test_coordination_durability.py $(PYTEST_FLAGS) -m slow

faults:
	python -m tfidf_tpu faults list

bench:
	python bench.py

probe-overlap:
	python probe_overlap.py

"""Probe: REAL multi-process ``jax.distributed`` at corpus scale.

Spawns N OS processes (CPU backend, 2 virtual devices each), joins them
with ``jax.distributed.initialize`` into one global device view, and runs
the mesh engine's ingest + commit + search over a ("docs", "terms") mesh
whose docs axis SPANS process boundaries — the global-df psum and top-k
all_gather run over the gloo collective backend, the same SPMD shape a
DCN-connected TPU pod executes (SURVEY.md §5.8). Every process checks
oracle parity (vs the single-device local engine on identical inputs) and
process 0 writes ``MULTIHOST.json``.

Usage: python probe_multihost.py            (parent; writes the artifact)
       python probe_multihost.py worker ... (subprocess body)
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time

N_PROCESSES = 4
DEVICES_PER_PROC = 2
N_DOCS = 2000
VOCAB = 5000
AVG_LEN = 40
N_QUERIES = 64


def worker(coord: str, n: int, pid: int) -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={DEVICES_PER_PROC}")
    import jax
    import numpy as np

    from tfidf_tpu.parallel.mesh import initialize_multihost, make_mesh

    assert initialize_multihost(coord, num_processes=n, process_id=pid)
    n_dev = len(jax.devices())
    assert n_dev == n * DEVICES_PER_PROC

    from tfidf_tpu.engine.engine import Engine
    from tfidf_tpu.utils.config import Config

    rng = np.random.default_rng(11)   # identical corpus on every process
    texts = []
    for _ in range(N_DOCS):
        ln = max(int(rng.poisson(AVG_LEN)), 3)
        ids = rng.zipf(1.3, size=ln) % VOCAB
        texts.append(" ".join(f"t{w}" for w in ids))
    queries = []
    for _ in range(N_QUERIES):
        ids = rng.zipf(1.3, size=int(rng.integers(2, 5))) % VOCAB
        queries.append(" ".join(f"t{w}" for w in ids))

    def cfg(sub: str, mode: str) -> Config:
        return Config(documents_path=f"/tmp/probe_mh_{pid}_{sub}",
                      engine_mode=mode, mesh_layout="ell",
                      min_doc_capacity=256, min_nnz_capacity=1 << 14,
                      min_vocab_capacity=1 << 13, query_batch=32,
                      max_query_terms=8)

    mesh = make_mesh((n_dev // 2, 2))
    eng = Engine(cfg("m", "mesh"), mesh=mesh)
    local = Engine(cfg("l", "local"))

    t0 = time.perf_counter()
    for i, t in enumerate(texts):
        eng.ingest_text(f"d{i}", t)
    ingest_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    eng.commit()
    commit_s = time.perf_counter() - t0

    for i, t in enumerate(texts):
        local.ingest_text(f"d{i}", t)
    local.commit()

    eng.search_batch(queries[:32])   # warm
    t0 = time.perf_counter()
    got = eng.search_batch(queries)
    search_s = time.perf_counter() - t0
    want = local.search_batch(queries)

    for qi, (g, w) in enumerate(zip(got, want)):
        gs = sorted((round(h.score, 4) for h in g), reverse=True)
        ws = sorted((round(h.score, 4) for h in w), reverse=True)
        # exact score multiset parity; names must match exactly above
        # the k-boundary score (WHICH of several boundary-tied docs make
        # the cut is legitimately layout-dependent)
        assert gs == ws, (qi, queries[qi], gs, ws)
        if gs:
            boundary = gs[-1]
            gn = {h.name for h in g if round(h.score, 4) > boundary}
            wn = {h.name for h in w if round(h.score, 4) > boundary}
            assert gn == wn, (qi, queries[qi], gn, wn)

    result = {
        "num_processes": n, "devices": n_dev,
        "mesh": {"docs": n_dev // 2, "terms": 2},
        "collective_backend": "gloo (cpu); ICI/DCN on TPU pods",
        "n_docs": N_DOCS, "n_queries": N_QUERIES,
        "ingest_s": round(ingest_s, 2), "commit_s": round(commit_s, 2),
        "search_qps": round(N_QUERIES / search_s, 1),
        "parity": "mesh == local engine top-10, all queries, "
                  "checked on every process",
        "layout": "ell",
    }
    if pid == 0:
        with open(os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "MULTIHOST.json"), "w") as f:
            json.dump(result, f, indent=1)
    print(f"MULTIHOST_OK pid={pid} {json.dumps(result)}", flush=True)


def main() -> None:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    for k in ("XLA_FLAGS", "JAX_PLATFORMS", "TFIDF_JAX_PLATFORM"):
        env.pop(k, None)
    env["PYTHONPATH"] = os.path.dirname(os.path.abspath(__file__))
    procs = [subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "worker",
         f"127.0.0.1:{port}", str(N_PROCESSES), str(i)], env=env)
        for i in range(N_PROCESSES)]
    rc = [p.wait(timeout=900) for p in procs]
    assert all(r == 0 for r in rc), rc
    print("ALL_OK")


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "worker":
        worker(sys.argv[2], int(sys.argv[3]), int(sys.argv[4]))
    else:
        main()
